"""The fleet scheduler (igg/fleet.py) on the 8-device CPU mesh: queue
draining with per-job grid lifecycle, decomposition planning against the
live devices, launcher-fault retry with exponential backoff, SIGTERM/
preemption persistence through the queue journal, and elastic re-admission
onto different capacity — every path driven by the deterministic fleet
chaos injectors (`scheduler_fault`, `job_preempt_at`)."""

import json

import numpy as np
import pytest

import igg
from igg.fleet import plan_dims
from helpers import ensemble_member_step, ensemble_states


def _make_states(seed, members):
    """Member states built from a decomposition-INVARIANT global random
    field (wrap-indexed per block), so elastic resume comparisons are
    meaningful across dims."""
    def build(grid):
        rng = np.random.default_rng(seed)
        g = [grid.dims[d] * (grid.nxyz[d] - grid.overlaps[d])
             for d in range(3)]
        out = []
        for _ in range(members):
            glob = rng.standard_normal(g)

            def block(coords, ls, glob=glob):
                idx = [(coords[d] * (ls[d] - grid.overlaps[d])
                        + np.arange(ls[d])) % g[d] for d in range(3)]
                return glob[np.ix_(*idx)]

            T = igg.from_local_blocks(block, tuple(grid.nxyz))
            out.append({"T": igg.update_halo(T)})
        return out
    return build


def _job(name, seed=1, members=2, n_steps=10, **kw):
    args = dict(name=name, global_interior=(8, 8, 8), members=members,
                n_steps=n_steps, make_states=_make_states(seed, members),
                step_fn=ensemble_member_step(), watch_every=5,
                checkpoint_every=5)
    args.update(kw)
    return igg.Job(**args)


# ---------------------------------------------------------------------------
# Decomposition planning
# ---------------------------------------------------------------------------

def test_plan_dims_balanced_and_divisible():
    dims, local = plan_dims((8, 8, 8), 8)
    assert dims == (2, 2, 2) and local == (6, 6, 6)
    dims, local = plan_dims((8, 8, 8), 4)
    assert np.prod(dims) == 4 and all(
        d * (n - 2) == 8 for d, n in zip(dims, local))
    dims, local = plan_dims((8, 8, 8), 1)
    assert dims == (1, 1, 1) and local == (10, 10, 10)
    # Open boundaries: global = dims*(n-ol) + ol.
    dims, local = plan_dims((10, 10, 10), 8, periods=(0, 0, 0))
    assert all(d * (n - 2) + 2 == 10 for d, n in zip(dims, local))
    # A prime interior that no 8-way split divides falls back to fewer
    # devices rather than failing.
    dims, _ = plan_dims((7, 7, 7), 8)
    assert np.prod(dims) == 7
    with pytest.raises(igg.GridError, match="no decomposition"):
        plan_dims((1, 8, 8), 8, periods=(0, 0, 0))   # nx would be 1


# ---------------------------------------------------------------------------
# Queue draining + journal
# ---------------------------------------------------------------------------

def test_queue_drains_and_journal_records(tmp_path):
    jobs = [_job("a", seed=1), _job("b", seed=2, members=4)]
    res = igg.run_fleet(jobs, tmp_path)
    assert not res.preempted
    assert all(o.status == "done" for o in res.jobs.values())
    assert res.jobs["a"].dims == (2, 2, 2)
    j = json.loads((tmp_path / "journal.json").read_text())
    assert j["format"] == "igg-fleet-journal-v1"
    assert {n: r["status"] for n, r in j["jobs"].items()} == {
        "a": "done", "b": "done"}
    assert j["jobs"]["b"]["steps_done"] == 10
    # Per-job event streams carry the job name.
    assert all(e.detail["job"] == "a" for e in res.jobs["a"].events)
    assert not igg.grid_is_initialized()     # scheduler owns grid lifecycle


def test_member_fault_isolated_inside_job(tmp_path):
    """A member NaN inside a job is the ensemble tier's problem: the job
    completes 'done' with zero quarantines and the queue never notices."""
    jobs = [_job("a", chaos=igg.chaos.ChaosPlan(nan_at=[(3, 1, "T")])),
            _job("b", seed=2)]
    res = igg.run_fleet(jobs, tmp_path)
    assert all(o.status == "done" for o in res.jobs.values())
    assert res.jobs["a"].result.quarantined == []
    assert any(e.kind == "member_rollback" for e in res.jobs["a"].events)


def test_resume_skips_done_jobs(tmp_path):
    jobs = [_job("a")]
    res = igg.run_fleet(jobs, tmp_path)
    assert res.jobs["a"].status == "done" and res.jobs["a"].attempts == 1
    res2 = igg.run_fleet(jobs, tmp_path, resume=True)
    assert res2.jobs["a"].status == "done"
    assert res2.jobs["a"].result is None       # skipped, not re-run
    j = json.loads((tmp_path / "journal.json").read_text())
    assert j["jobs"]["a"]["attempts"] == 1


# ---------------------------------------------------------------------------
# Launcher faults: retry with exponential backoff
# ---------------------------------------------------------------------------

def test_scheduler_fault_retried_with_backoff(tmp_path):
    jobs = [_job("a")]
    with igg.chaos.scheduler_fault("a", times=2):
        res = igg.run_fleet(jobs, tmp_path, backoff=0.01)
    o = res.jobs["a"]
    assert o.status == "done" and o.attempts == 3
    fails = [e for e in o.events if e.kind == "job_failed"]
    assert len(fails) == 2
    assert "InjectedSchedulerFault" in fails[0].detail["error"]


def test_fault_exhaustion_fails_job_queue_continues(tmp_path):
    """A job that keeps faulting is marked failed after the budget; the
    NEXT job still runs — one bad config cannot starve the fleet."""
    jobs = [_job("a"), _job("b", seed=2)]
    with igg.chaos.scheduler_fault("a", times=10):
        res = igg.run_fleet(jobs, tmp_path, backoff=0.01,
                            max_job_retries=2)
    assert res.jobs["a"].status == "failed"
    assert res.jobs["a"].attempts == 3
    assert any(e.kind == "job_gave_up" for e in res.jobs["a"].events)
    assert res.jobs["b"].status == "done"
    j = json.loads((tmp_path / "journal.json").read_text())
    assert j["jobs"]["a"]["status"] == "failed"


# ---------------------------------------------------------------------------
# Preemption + elastic re-admission
# ---------------------------------------------------------------------------

def test_preempt_persists_queue_and_elastic_resume(tmp_path):
    """job_preempt_at preempts job 'a' mid-run: its final generation and
    the journal persist, the rest of the queue stays queued; a resumed
    fleet on HALF the devices re-admits both — the preempted job resumes
    elastically (different dims) and finishes bit-identical to an
    uninterrupted run of the same job."""
    import jax

    jobs = [_job("a"), _job("b", seed=2)]
    with igg.chaos.job_preempt_at("a", 5):
        res = igg.run_fleet(jobs, tmp_path)
    assert res.preempted
    assert res.jobs["a"].status == "preempted"
    assert res.jobs["a"].result.steps_done == 5
    assert res.jobs["b"].status == "queued"
    j = json.loads((tmp_path / "journal.json").read_text())
    assert j["jobs"]["a"]["status"] == "preempted"

    res2 = igg.run_fleet(jobs, tmp_path, resume=True,
                         devices=jax.devices()[:4])
    assert all(o.status == "done" for o in res2.jobs.values())
    assert any(e.kind == "job_resumed" for e in res2.jobs["a"].events)
    assert res2.jobs["a"].dims != (2, 2, 2)        # genuinely re-planned
    # Bit-exactness oracle: an uninterrupted run of the same job on the
    # original capacity; interiors compared through a common restore.
    res3 = igg.run_fleet([_job("a")], tmp_path / "clean")

    def final_interior(ring_dir):
        igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=2,
                             periodx=1, periody=1, periodz=1, quiet=True)
        out = igg.load_checkpoint(igg.latest_checkpoint(ring_dir, "ens"),
                                  redistribute=True)
        got = np.asarray(igg.gather_interior(out["T"]))
        igg.finalize_global_grid()
        return got

    np.testing.assert_array_equal(
        final_interior(tmp_path / "jobs" / "a"),
        final_interior(tmp_path / "clean" / "jobs" / "a"))


def test_batch_packing_job_plans_single_device_grid(tmp_path):
    """A Job with packing='batch' must be planned onto the degenerate
    single-device grid (the member axis spans the devices), not failed
    because the domain also decomposes."""
    job = _job("a", members=8, packing="batch")
    res = igg.run_fleet([job], tmp_path)
    o = res.jobs["a"]
    assert o.status == "done", o.error
    assert o.dims == (1, 1, 1)
    assert o.result.packing == "batch"


def test_terminal_run_errors_fail_without_retry(tmp_path):
    """Deterministic run-level failures (an all-quarantined ensemble's
    ResilienceError, an invalid-config GridError) are NOT retried as
    launcher faults: the job fails on attempt 1 and the queue drains on."""
    doomed = _job("a", chaos=igg.chaos.ChaosPlan(
        nan_at=[(3, 0, "T"), (3, 1, "T")]))   # both members: all-quarantine
    doomed.ring = 0                            # invalid config -> GridError
    jobs = [doomed, _job("b", seed=2)]
    res = igg.run_fleet(jobs, tmp_path, backoff=0.01, max_job_retries=5)
    assert res.jobs["a"].status == "failed"
    assert res.jobs["a"].attempts == 1         # no backoff retries burned
    gave = next(e for e in res.jobs["a"].events if e.kind == "job_gave_up")
    assert gave.detail["terminal"] is True
    assert res.jobs["b"].status == "done"


def test_run_fleet_rejects_live_grid(tmp_path):
    igg.init_global_grid(6, 6, 6, quiet=True)
    with pytest.raises(igg.GridError, match="finalize"):
        igg.run_fleet([_job("a")], tmp_path)
    igg.finalize_global_grid()
    with pytest.raises(igg.GridError, match="duplicate"):
        igg.run_fleet([_job("a"), _job("a")], tmp_path)


# ---------------------------------------------------------------------------
# Journal identity: the config hash guards resumed-name matches
# ---------------------------------------------------------------------------

def test_resume_reused_name_different_config_is_fresh(tmp_path):
    """A resumed journal matches a job by more than its name: a reused
    name with a DIFFERENT config (here: more steps) is a fresh job — the
    stale record and ring are dropped with a `job_name_reused` warning,
    never silently skipped as done or resumed from the other config's
    ring."""
    igg.run_fleet([_job("a", n_steps=10)], tmp_path)
    j = json.loads((tmp_path / "journal.json").read_text())
    assert j["jobs"]["a"]["config_hash"]

    events = []
    res = igg.run_fleet([_job("a", n_steps=20)], tmp_path, resume=True,
                        on_event=events.append)
    assert res.jobs["a"].status == "done"
    assert res.jobs["a"].result is not None          # genuinely re-run
    assert res.jobs["a"].result.steps_done == 20
    reused = [e for e in events if e.kind == "job_name_reused"]
    assert len(reused) == 1
    assert reused[0].detail["prior_status"] == "done"
    assert reused[0].detail["prior_config_hash"] != \
        reused[0].detail["config_hash"]
    j = json.loads((tmp_path / "journal.json").read_text())
    assert j["jobs"]["a"]["steps_done"] == 20
    assert j["jobs"]["a"]["config_hash"] == reused[0].detail["config_hash"]


def test_resume_same_config_still_skips(tmp_path):
    """The other direction: an identical config under the same name keeps
    the journal-identity contract of PR 13 — resume skips it as done, no
    reset, no warning."""
    igg.run_fleet([_job("a")], tmp_path)
    events = []
    res = igg.run_fleet([_job("a")], tmp_path, resume=True,
                        on_event=events.append)
    assert res.jobs["a"].status == "done"
    assert res.jobs["a"].result is None              # skipped, not re-run
    assert not any(e.kind == "job_name_reused" for e in events)
