"""The always-on fleet service (igg/serve.py) on the 8-device CPU mesh:
admission control with structured verdicts, backpressure and shedding,
concurrent jobs on disjoint device subsets behind thread-scoped grid
lifecycles, weighted-fair multi-tenant scheduling with poison-job
quarantine, priority preemption, device fencing, the graceful drain
protocol with `resume=True` reconciliation, and the hostile-intake chaos
injectors (`arrival_storm`, `malformed_submission`)."""

import json
import threading
import time

import numpy as np
import pytest

import igg
from igg import serve as iserve
from igg import shared as ishared
from igg.resilience import (PreemptionCell, preemption_scope,
                            preemption_requested, request_preemption,
                            clear_preemption)
from helpers import ensemble_member_step


def _make_states(seed, members):
    """Decomposition-invariant member states (the test_fleet idiom) so
    bit-exactness comparisons survive elastic re-planning."""
    def build(grid):
        rng = np.random.default_rng(seed)
        g = [grid.dims[d] * (grid.nxyz[d] - grid.overlaps[d])
             for d in range(3)]
        out = []
        for _ in range(members):
            glob = rng.standard_normal(g)

            def block(coords, ls, glob=glob):
                idx = [(coords[d] * (ls[d] - grid.overlaps[d])
                        + np.arange(ls[d])) % g[d] for d in range(3)]
                return glob[np.ix_(*idx)]

            T = igg.from_local_blocks(block, tuple(grid.nxyz))
            out.append({"T": igg.update_halo(T)})
        return out
    return build


def _factory(spec):
    members = spec.get("members", 1)
    job = igg.Job(name=spec["name"], global_interior=(8, 8, 8),
                  members=members, n_steps=spec["n_steps"],
                  make_states=_make_states(spec.get("seed", 1), members),
                  step_fn=ensemble_member_step(), watch_every=5,
                  checkpoint_every=spec.get("checkpoint_every", 5))
    if spec.get("doom"):
        job.ring = 0          # invalid config -> terminal GridError
    return job


def _spec(name, n_steps=8, **kw):
    out = {"name": name, "global_interior": [8, 8, 8], "members": 2,
           "n_steps": n_steps}
    out.update(kw)
    return out


def _wait(pred, timeout=90, poll=0.02):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(poll)
    return False


class _Serve:
    """serve_fleet on a background thread, driven via ServeControl."""

    def __init__(self, workdir, factory=_factory, **kw):
        self.ctl = igg.ServeControl()
        self.events = []
        self.error = None
        self.result = None
        kw.setdefault("stop_when_idle_s", 0.8)
        kw.setdefault("poll_s", 0.02)
        kw.setdefault("install_sigterm", False)
        kw.setdefault("backoff", 0.01)

        def run():
            try:
                self.result = igg.serve_fleet(
                    workdir, factory, control=self.ctl,
                    on_event=self.events.append, **kw)
            except BaseException as e:       # surfaced on __exit__
                self.error = e

        self.thread = threading.Thread(target=run)

    def __enter__(self):
        self.thread.start()
        assert self.ctl.wait_ready(30)
        return self

    def __exit__(self, *exc):
        self.thread.join(timeout=240)
        assert not self.thread.is_alive(), "serve loop did not exit"
        if self.error is not None and not exc[0]:
            raise self.error

    def kinds(self, kind):
        return [e for e in list(self.events) if e.kind == kind]


def _final_interior(ring_dir):
    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    out = igg.load_checkpoint(igg.latest_checkpoint(ring_dir, "ens"),
                              redistribute=True)
    got = np.asarray(igg.gather_interior(out["T"]))
    igg.finalize_global_grid()
    return got


# ---------------------------------------------------------------------------
# Thread-scoped grid + preemption (the substrate of concurrent jobs)
# ---------------------------------------------------------------------------

def test_thread_grid_scope_isolates_from_process_global():
    assert not igg.grid_is_initialized()
    seen = {}

    def body():
        with ishared.thread_grid_scope():
            assert not igg.grid_is_initialized()
            igg.init_global_grid(6, 6, 6, quiet=True)
            seen["inside"] = igg.grid_is_initialized()
            seen["epoch"] = ishared.grid_epoch()
            igg.finalize_global_grid()
        seen["after"] = igg.grid_is_initialized()

    t = threading.Thread(target=body)
    t.start()
    t.join(timeout=60)
    assert seen == {"inside": True, "epoch": seen["epoch"], "after": False}
    # The scoped epoch came from the shared counter: a scoped grid can
    # never collide with the process-global grid's compiled-cache keys.
    assert seen["epoch"] != ishared.grid_epoch()
    assert not igg.grid_is_initialized()


def test_preemption_cell_scoped_to_thread():
    cell = PreemptionCell()
    hits = {}

    def body():
        with preemption_scope(cell):
            request_preemption()           # lands in the CELL
            hits["scoped"] = preemption_requested()
            clear_preemption()             # clears only the cell
            hits["cleared"] = preemption_requested()

    t = threading.Thread(target=body)
    t.start()
    t.join(timeout=30)
    assert hits == {"scoped": True, "cleared": False}
    assert not preemption_requested()      # global flag never touched
    # An external request on the cell reaches the scoped thread only.
    cell.request()
    assert cell.requested() and not preemption_requested()


# ---------------------------------------------------------------------------
# Admission control: the verdict table
# ---------------------------------------------------------------------------

def test_admission_verdicts(tmp_path):
    events = []
    cfg = dict(max_concurrent=2, queue_bound=3, tenant_queue_bound=2,
               tenant_retry_budget=4, poll_s=0.01, max_body=200)
    st = iserve._ServeState(tmp_path, _factory, __import__("jax").devices(),
                            cfg, None, events.append, None)

    # Malformed / oversized / structurally invalid: 400 at the door.
    assert (st.submit(b"{not json").code, ) == (400, )
    assert "malformed" in st.submit(b"{not json").reason
    assert "oversized" in st.submit(b"x" * 500).reason
    assert "name" in st.submit({"name": "bad name!", "n_steps": 1}).reason
    assert "n_steps" in st.submit(
        {"name": "a", "global_interior": [8, 8, 8]}).reason
    assert "oversized" in st.submit(
        {"name": "a", "global_interior": [8, 8, 10 ** 7],
         "n_steps": 1}).reason
    # Inadmissible decomposition: plan_dims feasibility at the door.
    inf = st.submit({"name": "inf", "global_interior": [2, 2, 2],
                     "overlaps": [4, 4, 4], "n_steps": 1})
    assert inf.code == 400 and inf.reason.startswith("infeasible")

    # Admission + idempotency on (tenant, name, submit_token).
    ok = st.submit(_spec("j1", submit_token="t1"))
    assert (ok.code, ok.status) == (201, "admitted")
    dup = st.submit(_spec("j1", submit_token="t1"))
    assert (dup.code, dup.status) == (200, "duplicate")
    clash = st.submit(_spec("j1", submit_token="OTHER"))
    assert (clash.code, clash.reason) == (409, "name_in_use")

    # Journal record carries the multi-tenant identity fields.
    rec = st.journal["jobs"]["j1"]
    assert rec["tenant"] == "default" and rec["status"] == "queued"
    assert rec["config_hash"] and rec["submit_token"] == "t1"
    assert rec["submitted_at"] > 0 and isinstance(rec["spec"], dict)

    # A quarantined name never re-admits; a done name is a duplicate.
    for name, status, code, why in (("qq", "quarantined", 409,
                                     "quarantined"),
                                    ("dd", "done", 200, "already done")):
        spec, _ = st._validate(_spec(name, tenant="term"))
        st.journal["jobs"][name] = {"status": status,
                                    "config_hash": st._spec_hash(spec)}
        got = st.submit(_spec(name, tenant="term"))
        assert (got.code, got.reason) == (code, why)

    # Same name, DIFFERENT config hash: fresh job + job_name_reused.
    reused = st.submit(_spec("dd", tenant="term", n_steps=99))
    assert (reused.code, reused.status) == (201, "admitted")
    ev = [e for e in events if e.kind == "job_name_reused"]
    assert len(ev) == 1 and ev[0].detail["prior_status"] == "done"

    # Per-tenant bound, then the global bound: 429 with distinct reasons.
    assert st.submit(_spec("j2")).code == 201       # global depth now 3
    full = st.submit(_spec("j3"))
    assert (full.code, full.reason) == (429, "tenant_queue_full")
    sat = st.submit(_spec("j4", tenant="third"))
    assert (sat.code, sat.reason) == (429, "queue_saturated")

    # Tenant retry budget exhausted: its submissions shed.
    st._tenant("greedy")["retries_used"] = 99
    broke = st.submit(_spec("j6", tenant="greedy"))
    assert (broke.code, broke.reason) == (429, "tenant_budget_exhausted")

    # Draining: intake answers 503.
    st.draining = True
    drain = st.submit(_spec("late"))
    assert (drain.code, drain.reason) == (503, "draining")

    # The shed/rejected ledgers reconcile with per-tenant accounting.
    assert sum(t["shed"] for t in st.tenants.values()) == len(st.shed)
    assert len([e for e in events if e.kind == "job_shed"]) == len(st.shed)


def test_serve_rejects_live_grid(tmp_path):
    igg.init_global_grid(6, 6, 6, quiet=True)
    with pytest.raises(igg.GridError, match="finalize"):
        igg.serve_fleet(tmp_path, _factory, stop_when_idle_s=0.1)
    igg.finalize_global_grid()


# ---------------------------------------------------------------------------
# Concurrent jobs on disjoint subsets + bit-exactness
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_concurrent_disjoint_subsets_bit_exact(tmp_path):
    """Two tenants' jobs run CONCURRENTLY on disjoint 4-device subsets
    (observed via the live stats snapshot) and each finishes bit-identical
    to the same job run alone through igg.run_fleet."""
    with _Serve(tmp_path / "serve", max_concurrent=2) as s:
        a = s.ctl.submit(_spec("a", tenant="alice", seed=1, n_steps=40))
        b = s.ctl.submit(_spec("b", tenant="bob", seed=2, n_steps=40))
        assert a.code == 201 and b.code == 201
        assert _wait(lambda: len(s.ctl.stats()["running"]) == 2), \
            "jobs never overlapped"
    r = s.result
    assert r.jobs["a"].status == "done" and r.jobs["b"].status == "done"
    assert not igg.grid_is_initialized()

    # Serial oracle on the full mesh.
    def _job(name, seed):
        return igg.Job(name=name, global_interior=(8, 8, 8), members=2,
                       n_steps=40, make_states=_make_states(seed, 2),
                       step_fn=ensemble_member_step(), watch_every=5,
                       checkpoint_every=5)
    igg.run_fleet([_job("a", 1), _job("b", 2)], tmp_path / "serial")
    for name in ("a", "b"):
        np.testing.assert_array_equal(
            _final_interior(tmp_path / "serve" / "jobs" / name),
            _final_interior(tmp_path / "serial" / "jobs" / name))


# ---------------------------------------------------------------------------
# Priority preemption / deadlines / fencing
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_priority_preempts_running_job(tmp_path):
    """A hot submission that cannot be placed preempts the lowest-priority
    running job through ITS cell: the victim seals a generation, re-admits,
    and BOTH finish done."""
    with _Serve(tmp_path, max_concurrent=2) as s:
        low = s.ctl.submit(_spec("low", n_devices=8, n_steps=4000,
                                 checkpoint_every=500))
        assert low.code == 201
        assert _wait(lambda: "low" in s.ctl.stats()["running"])
        hot = s.ctl.submit(_spec("hot", priority=5, n_steps=8))
        assert hot.code == 201
        assert _wait(lambda: any(e.kind == "job_requeued"
                                 and e.detail["reason"] == "priority"
                                 for e in list(s.events)))
    r = s.result
    assert r.jobs["hot"].status == "done"
    assert r.jobs["low"].status == "done"
    assert any(e.kind == "job_resumed" for e in s.events)


def test_deadline_expired_submission_sheds(tmp_path):
    with _Serve(tmp_path, max_concurrent=1) as s:
        s.ctl.submit(_spec("big", n_devices=8, n_steps=2000,
                           checkpoint_every=500))
        assert _wait(lambda: "big" in s.ctl.stats()["running"])
        s.ctl.submit(_spec("urgent", deadline_s=0.1, n_steps=4))
        assert _wait(lambda: any(
            e.kind == "job_shed"
            and e.detail["reason"] == "deadline_exceeded"
            for e in list(s.events)))
    r = s.result
    assert "urgent" not in r.jobs
    shed = [x for x in r.shed if x["job"] == "urgent"]
    assert shed and shed[0]["reason"] == "deadline_exceeded"
    # A deadline-shed submission leaves no journal residue.
    j = json.loads(r.journal.read_text())
    assert "urgent" not in j["jobs"]


@pytest.mark.slow
def test_fence_device_shrinks_only_its_jobs(tmp_path):
    """Fencing one device preempts exactly the jobs whose subset holds it
    (here: the first-launched job on devices[0:4]); the disjoint job is
    untouched and the victim resumes elastically on a shrunk pool."""
    with _Serve(tmp_path, max_concurrent=2) as s:
        s.ctl.submit(_spec("a", tenant="alice", n_steps=4000,
                           checkpoint_every=500))
        assert _wait(lambda: "a" in s.ctl.stats()["running"])
        s.ctl.submit(_spec("b", tenant="bob", seed=2, n_steps=4000,
                           checkpoint_every=500))
        assert _wait(lambda: len(s.ctl.stats()["running"]) == 2)
        s.ctl.fence_device(0)
        assert _wait(lambda: s.kinds("device_fenced"))
        assert _wait(lambda: any(e.detail["reason"] == "fence"
                                 for e in s.kinds("job_requeued")))
    r = s.result
    fence = s.kinds("device_fenced")[0]
    assert fence.detail["device"] == 0 and fence.detail["jobs"] == ["a"]
    # Only the victim was requeued; the disjoint job ran straight through.
    assert {e.detail["job"] for e in s.kinds("job_requeued")} == {"a"}
    assert r.jobs["a"].status == "done" and r.jobs["b"].status == "done"
    # The elastic resume re-planned onto fewer devices than the original
    # half-mesh share.
    assert int(np.prod(r.jobs["a"].dims)) < 4


# ---------------------------------------------------------------------------
# Drain + resume
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_drain_seals_and_resume_is_bit_exact(tmp_path):
    """ServeControl.drain (the SIGTERM path): intake stops with 503, the
    running job seals a generation and stays journaled `preempted`, the
    journal seals — and a resume=True relaunch finishes it bit-identical
    to an uninterrupted run."""
    wd = tmp_path / "serve"
    with _Serve(wd, stop_when_idle_s=None) as s:
        s.ctl.submit(_spec("a", n_steps=4000, checkpoint_every=500,
                           n_devices=8))
        assert _wait(lambda: "a" in s.ctl.stats()["running"])
        # Queued behind "a" (which holds all 8 devices): the drain must
        # leave it journaled `queued`, NOT launch it onto the devices
        # the sealing worker releases.
        s.ctl.submit(_spec("q", n_steps=4))
        s.ctl.drain()
        late = s.ctl.submit(_spec("late"))
        assert (late.code, late.reason) == (503, "draining")
    r = s.result
    assert r.drained and r.jobs["a"].status == "preempted"
    assert "q" not in r.jobs
    j = json.loads(r.journal.read_text())
    assert j["jobs"]["a"]["status"] == "preempted"
    assert j["jobs"]["q"]["status"] == "queued"
    assert j["sealed_at"] > 0

    with _Serve(wd, resume=True) as s2:
        pass
    r2 = s2.result
    assert r2.jobs["a"].status == "done"
    assert r2.jobs["q"].status == "done"
    assert any(e.detail.get("source") == "resume"
               for e in s2.kinds("job_admitted"))

    igg.run_fleet([igg.Job(name="a", global_interior=(8, 8, 8), members=2,
                           n_steps=4000, make_states=_make_states(1, 2),
                           step_fn=ensemble_member_step(), watch_every=5,
                           checkpoint_every=500)], tmp_path / "clean")
    np.testing.assert_array_equal(
        _final_interior(wd / "jobs" / "a"),
        _final_interior(tmp_path / "clean" / "jobs" / "a"))


# ---------------------------------------------------------------------------
# Quarantine + tenant isolation
# ---------------------------------------------------------------------------

def test_poison_job_quarantined_and_never_readmitted(tmp_path):
    with _Serve(tmp_path) as s:
        assert s.ctl.submit(_spec("poison", doom=True, n_steps=4,
                                  submit_token="t")).code == 201
        assert _wait(lambda: s.kinds("job_quarantined"))
        again = s.ctl.submit(_spec("poison", doom=True, n_steps=4,
                                   submit_token="t2"))
        assert (again.code, again.reason) == (409, "quarantined")
    r = s.result
    assert r.jobs["poison"].status == "quarantined"
    j = json.loads(r.journal.read_text())
    assert j["jobs"]["poison"]["status"] == "quarantined"
    assert r.tenants["default"]["quarantined"] == 1

    # resume=True leaves the quarantined record terminal.
    with _Serve(tmp_path, resume=True) as s2:
        pass
    assert "poison" not in s2.result.jobs
    assert json.loads(s2.result.journal.read_text())[
        "jobs"]["poison"]["status"] == "quarantined"


@pytest.mark.slow
def test_two_tenant_isolation_hostile_vs_healthy(tmp_path):
    """Satellite: a hostile tenant (poison jobs + a submission flood)
    burns ITS budget and floods ITS queue; the healthy tenant's jobs all
    finish bit-identical to an unloaded run, and every refusal is
    accounted — shed/rejected ledgers, per-tenant counters and the
    journal reconcile exactly."""
    with _Serve(tmp_path / "serve", max_concurrent=2,
                tenant_queue_bound=2, tenant_retry_budget=2) as s:
        assert s.ctl.submit(_spec("h1", tenant="healthy", seed=1,
                                  n_steps=20)).code == 201
        assert s.ctl.submit(_spec("m1", tenant="mallory", doom=True,
                                  n_steps=4)).code == 201
        assert s.ctl.submit(_spec("h2", tenant="healthy", seed=2,
                                  n_steps=20)).code == 201
        # Flood: the tenant bound (2) sheds the excess without touching
        # the healthy queue.
        codes = [s.ctl.submit(_spec(f"m{i}", tenant="mallory",
                                    doom=True, n_steps=4)).code
                 for i in range(2, 8)]
        assert 429 in codes
        # After the first quarantine burns the 2-strike budget, mallory
        # sheds at the DOOR with tenant_budget_exhausted.
        assert _wait(lambda: s.kinds("job_quarantined"))
        broke = s.ctl.submit(_spec("m99", tenant="mallory", doom=True,
                                   n_steps=4))
        assert (broke.code, broke.reason) == (429,
                                              "tenant_budget_exhausted")
    r = s.result
    assert r.jobs["h1"].status == "done"
    assert r.jobs["h2"].status == "done"
    mal = r.tenants["mallory"]
    assert mal["quarantined"] >= 1 and mal["shed"] >= 2
    assert mal["retries_used"] >= mal["retry_budget"]
    assert r.tenants["healthy"]["shed"] == 0
    assert r.tenants["healthy"]["rejected"] == 0

    # Accounting reconciliation: ledgers == per-tenant counters == events.
    assert sum(t["shed"] for t in r.tenants.values()) == len(r.shed)
    assert sum(t["rejected"] for t in r.tenants.values()) == len(
        r.rejected)
    assert len(s.kinds("job_shed")) == len(r.shed)
    j = json.loads(r.journal.read_text())
    assert j["jobs"]["h1"]["status"] == "done"
    assert all(rec["status"] in ("done", "quarantined", "queued")
               for rec in j["jobs"].values())

    # Healthy tenant bit-exactness under hostile load.
    def _job(name, seed):
        return igg.Job(name=name, global_interior=(8, 8, 8), members=2,
                       n_steps=20, make_states=_make_states(seed, 2),
                       step_fn=ensemble_member_step(), watch_every=5,
                       checkpoint_every=5)
    igg.run_fleet([_job("h1", 1), _job("h2", 2)], tmp_path / "clean")
    for name, seed in (("h1", 1), ("h2", 2)):
        np.testing.assert_array_equal(
            _final_interior(tmp_path / "serve" / "jobs" / name),
            _final_interior(tmp_path / "clean" / "jobs" / name))


# ---------------------------------------------------------------------------
# Hostile-intake chaos injectors
# ---------------------------------------------------------------------------

def test_arrival_storm_and_malformed_chaos(tmp_path):
    """arrival_storm floods the intake in one tick — the queue fills to
    its bound, the overflow sheds; malformed_submission is rejected at
    the door.  Both compose under igg.chaos.armed()."""
    storm = igg.chaos.arrival_storm(10, tenant="load",
                                    spec={"global_interior": [8, 8, 8],
                                          "members": 1, "n_steps": 2})
    with igg.chaos.armed(storm, igg.chaos.malformed_submission(times=2)):
        assert iserve._CHAOS_SUBMIT_TAP is not None
        with _Serve(tmp_path, max_concurrent=2, queue_bound=3,
                    tenant_queue_bound=8) as s:
            assert _wait(lambda: s.kinds("job_shed"))
    assert iserve._CHAOS_SUBMIT_TAP is None        # consumed one-shot
    r = s.result
    admitted = [e for e in s.kinds("job_admitted")
                if e.detail.get("source") == "storm"]
    assert len(admitted) + len(s.kinds("job_shed")) == 10
    assert r.tenants["load"]["shed"] == len(s.kinds("job_shed")) >= 1
    assert all(x["reason"] in ("queue_saturated", "tenant_queue_full")
               for x in r.shed)
    # Every admitted storm job actually ran to completion.
    assert all(r.jobs[e.detail["job"]].status == "done"
               for e in admitted)
    # The malformed bodies were rejected with the parse reason.
    mal = [x for x in r.rejected if x["source"] == "chaos"]
    assert len(mal) == 2 and all("malformed" in x["reason"] for x in mal)


# ---------------------------------------------------------------------------
# Spool intake
# ---------------------------------------------------------------------------

def test_spool_intake_and_rejected_quarantine_dir(tmp_path):
    import os

    with _Serve(tmp_path) as s:
        spool = tmp_path / "spool"
        assert _wait(lambda: spool.is_dir())
        tmp = spool / ".tmp-good"
        tmp.write_text(json.dumps(_spec("spooled", n_steps=4)))
        os.rename(tmp, spool / "good.json")       # atomic-rename protocol
        (spool / "bad.json").write_bytes(b"{nope")
        assert _wait(lambda: s.kinds("job_rejected"))
    r = s.result
    assert r.jobs["spooled"].status == "done"
    # The malformed body is preserved for post-mortem, not lost.
    assert (tmp_path / "spool" / "rejected" / "bad.json").exists()
    assert not list((tmp_path / "spool").glob("*.json"))
