"""Multi-process launcher for the CPU scaling harness (ISSUE 16 part 4).

Spawns N controller processes, each pinned to ONE virtual CPU device
(`--xla_force_host_platform_device_count=1`), joined into a single
N-device global mesh via ``jax.distributed.initialize`` — the
process-per-device layout of a real pod job (one controller per host),
shrunk onto localhost.  This is the cross-PROCESS complement of the
in-process 8-virtual-device mesh the rest of the suite runs on: the
collectives here cross process boundaries, so the zero-host-sync and
O(local) contracts are exercised against a genuinely distributed
runtime, not a shared address space.

Some jaxlib CPU backends cannot run cross-process computations at all
(no Gloo collectives); every such program fails with
:data:`NO_MULTIPROC`.  The launcher detects that and reports a SKIP
instead of a failure — same policy as ``tests/test_multihost.py``.

Standalone (the ci.sh hook):

    python tests/multiproc/launcher.py [nproc]

prints ``MULTIPROC-OK`` on success or ``SKIP: ...`` (exit 0 either
way); any real worker failure exits nonzero with the worker logs.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

# The sentinel jaxlib raises from every cross-process computation on CPU
# backends without cross-process collective support (kept verbatim in
# sync with tests/test_multihost.py).
NO_MULTIPROC = "Multiprocess computations aren't implemented"

SKIP_MESSAGE = ("this jaxlib's CPU backend has no cross-process "
                "computation support; run the multiproc harness on a "
                "backend with cross-process collectives")

# The smoke worker: halo exchange + overlapped-vs-sequential step on the
# cross-process mesh.  Each process owns exactly one device; the global
# grid spans all of them.  The overlapped (`hide_communication`) step
# must serve BITWISE-identical state to the sequential compute+exchange
# composition — the same contract weak_scaling.py's golden row pins on
# the in-process mesh, here crossing real process boundaries.
SMOKE_WORKER = r"""
import os, sys
pid, nproc, port, outdir = (int(sys.argv[1]), int(sys.argv[2]),
                            sys.argv[3], sys.argv[4])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
jax.distributed.initialize(coordinator_address="127.0.0.1:" + port,
                           num_processes=nproc, process_id=pid)
import numpy as np, igg
from igg.models import diffusion3d as d3
me, dims, nprocs, coords, mesh = igg.init_global_grid(
    8, 8, 8, periodx=1, periody=1, periodz=1, quiet=True)
assert nprocs == nproc, (nprocs, nproc)
assert me == pid
# 1) Halo-exchange smoke: a coordinate-filled field crosses process
#    boundaries; the gathered global array is checked against the
#    single-controller oracle by the caller.
A = igg.zeros((8, 8, 8))
X, Y, Z = igg.coord_fields(1.0, 1.0, 1.0, A)
A = igg.update_halo(A + X * 10000 + Y * 100 + Z)
gA = igg.gather(A)
# 2) Overlapped step vs sequential composition, bitwise, on the
#    cross-process mesh.
p = d3.Params()
T, Cp = d3.init_fields(p, np.float32)
seq = d3.make_multi_step(2, p, donate=False, use_pallas=False,
                         overlap=False, tune=False)
ov = d3.make_multi_step(2, p, donate=False, use_pallas=False,
                        overlap=True, tune=False)
a, b = seq(T, Cp), ov(T, Cp)
ga, gb = igg.gather(a), igg.gather(b)
if me == 0:
    assert gA is not None
    np.save(os.path.join(outdir, "halo.npy"), np.asarray(gA))
    assert ga is not None and gb is not None
    assert np.array_equal(np.asarray(ga), np.asarray(gb)), \
        "overlapped step diverged from the sequential composition"
    print("MULTIPROC-SMOKE-OK")
else:
    assert ga is None and gb is None
igg.finalize_global_grid()
"""


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def spawn(workdir, worker_src: str, *, nproc: int = 2, args=(),
          timeout: float = 240.0):
    """Launch `nproc` single-device controller processes of `worker_src`.

    Each worker receives argv ``(pid, nproc, port, *args)``.  Returns
    ``(logs, skipped)`` — `skipped` is True when the backend cannot run
    cross-process computations at all (:data:`NO_MULTIPROC` in any
    log).  Raises ``RuntimeError`` on worker failure or timeout, with
    the worker logs in the message."""
    port = str(free_port())
    worker = os.path.join(str(workdir), "multiproc_worker.py")
    with open(worker, "w") as f:
        f.write(worker_src)
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ, PYTHONPATH=repo)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # keep the TPU plugin out
    procs = [subprocess.Popen(
        [sys.executable, worker, str(p), str(nproc), port,
         *map(str, args)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for p in range(nproc)]
    logs = []
    try:
        for p in procs:
            logs.append(p.communicate(timeout=timeout)[0].decode())
    except subprocess.TimeoutExpired:
        # Don't leave orphans holding the coordinator port; surface
        # whatever the workers produced before hanging.
        partial = list(logs)
        for p in procs[len(logs):]:
            p.kill()
            rest, _ = p.communicate()
            partial.append((rest or b"").decode())
        raise RuntimeError("multiproc workers timed out; partial "
                           "output:\n" + "\n---\n".join(partial))
    if any(NO_MULTIPROC in log for log in logs):
        return logs, True
    bad = [(p, log) for p, log in zip(procs, logs) if p.returncode != 0]
    if bad:
        raise RuntimeError("multiproc worker(s) failed:\n"
                           + "\n---\n".join(log for _, log in bad))
    return logs, False


def main(argv) -> int:
    nproc = int(argv[1]) if len(argv) > 1 else 2
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        try:
            logs, skipped = spawn(td, SMOKE_WORKER, nproc=nproc,
                                  args=(td,))
        except RuntimeError as e:
            print(e)
            return 1
        if skipped:
            print("SKIP: " + SKIP_MESSAGE)
            return 0
        assert any("MULTIPROC-SMOKE-OK" in log for log in logs), logs
        print("MULTIPROC-OK")
        return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
