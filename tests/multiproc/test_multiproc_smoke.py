"""The multi-process scaling-harness smoke (ISSUE 16 part 4): N
single-device CPU controller processes form one global mesh over
``jax.distributed.initialize``; halo exchange crosses real process
boundaries and the overlapped (`hide_communication`) step serves
bitwise-identical state to the sequential composition there — the
cross-process proof of the contract the weak-scaling golden row pins on
the in-process virtual mesh.  Auto-SKIPs (launcher.SKIP_MESSAGE) on
jaxlib builds whose CPU backend has no cross-process collectives."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import launcher  # noqa: E402  (sibling module, path-inserted above)

import igg  # noqa: E402


@pytest.mark.slow
def test_two_process_halo_and_overlap_smoke(tmp_path):
    logs, skipped = launcher.spawn(tmp_path, launcher.SMOKE_WORKER,
                                   nproc=2, args=(str(tmp_path),))
    if skipped:
        pytest.skip(launcher.SKIP_MESSAGE)
    assert any("MULTIPROC-SMOKE-OK" in log for log in logs), logs

    # Single-controller oracle on the same 2-device global grid: the
    # cross-process halo exchange must produce the identical global
    # array.
    import jax

    igg.init_global_grid(8, 8, 8, periodx=1, periody=1, periodz=1,
                         quiet=True, devices=jax.devices()[:2])
    A = igg.zeros((8, 8, 8))
    X, Y, Z = igg.coord_fields(1.0, 1.0, 1.0, A)
    A = igg.update_halo(A + X * 10000 + Y * 100 + Z)
    want = np.asarray(igg.gather(A))
    igg.finalize_global_grid()

    got = np.load(tmp_path / "halo.npy")
    np.testing.assert_array_equal(got, want)


@pytest.mark.slow
def test_launcher_standalone_reports_ok_or_skip(tmp_path, capsys):
    """The ci.sh hook: `python tests/multiproc/launcher.py` must print
    MULTIPROC-OK or the explicit SKIP line and exit 0 — never fail
    silently."""
    rc = launcher.main(["launcher.py", "2"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert ("MULTIPROC-OK" in out) or ("SKIP: " in out), out
