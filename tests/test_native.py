"""Native runtime (igg/native): threaded re-tile + memcopy vs numpy oracles.

The native library is the TPU build's counterpart of the reference's
host-side copy machinery (`/root/reference/src/update_halo.jl:534-563`,
`/root/reference/src/gather.jl:63-66`); these tests pin its layout contract
to a pure-numpy implementation and check the wired `gather_interior` path
stays identical to the fallback.
"""

import numpy as np
import pytest

import igg
from igg import native


pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no compiler)")


def numpy_retile(stacked, dims, s, keep, full_last):
    out = stacked
    for d in range(3):
        pieces = []
        for c in range(dims[d]):
            block = np.take(out, range(c * s[d], (c + 1) * s[d]), axis=d)
            if c == dims[d] - 1 and full_last[d]:
                pieces.append(block)
            else:
                pieces.append(np.take(block, range(keep[d]), axis=d))
        out = np.concatenate(pieces, axis=d) if len(pieces) > 1 else pieces[0]
    return out


@pytest.mark.parametrize("dims,s,keep,full_last", [
    ((2, 2, 2), (5, 4, 6), (3, 2, 4), (1, 1, 1)),
    ((2, 2, 2), (5, 4, 6), (3, 2, 4), (0, 0, 0)),
    ((4, 1, 2), (6, 3, 5), (4, 3, 3), (1, 0, 1)),
    ((1, 1, 1), (7, 5, 3), (5, 3, 1), (0, 1, 0)),
    ((2, 3, 1), (4, 4, 9), (4, 2, 9), (0, 1, 1)),  # keep == s in x/z
])
@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int16,
                                   np.complex64])
def test_retile_matches_numpy(dims, s, keep, full_last, dtype):
    rng = np.random.default_rng(0)
    shape = tuple(d * ss for d, ss in zip(dims, s))
    stacked = (rng.standard_normal(shape) * 100).astype(dtype)
    want = numpy_retile(stacked, dims, s, keep, full_last)
    got = native.retile(stacked, dims, s, keep, full_last)
    assert got is not None
    assert got.dtype == want.dtype and got.shape == want.shape
    np.testing.assert_array_equal(got, want)


def test_retile_large_multithreaded():
    dims, s = (2, 2, 2), (40, 40, 40)
    keep, full_last = (38, 38, 38), (1, 1, 0)
    rng = np.random.default_rng(1)
    stacked = rng.standard_normal(tuple(d * ss for d, ss in zip(dims, s)))
    np.testing.assert_array_equal(
        native.retile(stacked, dims, s, keep, full_last),
        numpy_retile(stacked, dims, s, keep, full_last))


def test_retile_rejects_noncontiguous_and_wrong_rank():
    a = np.zeros((4, 4, 4))
    assert native.retile(a[:, ::2, :], (1, 1, 1), (4, 2, 4), (2, 1, 2),
                         (1, 1, 1)) is None
    assert native.retile(np.zeros((4, 4)), (1, 1, 1), (4, 4, 1), (2, 2, 1),
                         (1, 1, 1)) is None


def test_memcopy():
    src = np.random.default_rng(2).standard_normal((64, 64, 64))
    dst = np.empty_like(src)
    assert native.memcopy(dst, src)
    np.testing.assert_array_equal(dst, src)
    assert not native.memcopy(np.empty((2, 2)), src)  # size mismatch → fallback


def test_gather_interior_native_matches_fallback(eight_devices):
    """The wired 3-D hot path and the generic numpy path must agree."""
    igg.init_global_grid(6, 7, 8, periodx=1, quiet=True)
    A = igg.zeros((6, 7, 8))
    X, Y, Z = igg.coord_fields(1.0, 1.0, 1.0, A)
    A = A + (X * 10000 + Y * 100 + Z)
    native_out = igg.gather_interior(A)

    grid = igg.get_global_grid()
    stacked = np.asarray(A)
    local = grid.local_shape(A)
    ols = [grid.ol_of_local(d, local) for d in range(3)]
    want = numpy_retile(
        stacked, grid.dims, local,
        [local[d] - max(ols[d], 0) for d in range(3)],
        [not grid.periods[d] for d in range(3)])
    np.testing.assert_array_equal(native_out, want)
    igg.finalize_global_grid()


def test_retile_rejects_shape_mismatch():
    assert native.retile(np.zeros((4, 4, 4)), (2, 2, 2), (4, 4, 4),
                         (2, 2, 2), (1, 1, 1)) is None


def test_memcopy_rejects_readonly_dst():
    src = np.ones((8, 8))
    dst = np.zeros((8, 8))
    dst.flags.writeable = False
    assert not native.memcopy(dst, src)
