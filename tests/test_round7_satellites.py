"""Round-7 satellites: the stacked lane-active pair-emulated group update
(`igg.halo._stacked_lane64_update`), and the gather/checkpoint multi-host
memory-cliff warnings."""

import warnings

import numpy as np
import pytest

import igg
from igg import halo


def _f64_fields(n, shape=(8, 8, 8)):
    rng = np.random.default_rng(41)
    return tuple(
        igg.from_local_blocks(
            lambda coords, ls: rng.standard_normal(ls)
            + 7.0 * sum(coords), shape).astype(np.float64) + i
        for i in range(n))


def _with_stacked(flag, fields):
    """update_halo with the stacked-group seam set, fresh compile cache
    (the seam is not part of the compiled-program key)."""
    halo.free_update_halo_buffers()
    old = halo._FORCE_STACKED64
    halo._FORCE_STACKED64 = flag
    try:
        out = igg.update_halo(*fields)
    finally:
        halo._FORCE_STACKED64 = old
        halo.free_update_halo_buffers()
    return out if isinstance(out, tuple) else (out,)


@pytest.mark.parametrize("nfields", [2, 4])
def test_stacked64_update_matches_reference(nfields):
    """The stacked f64 group program must reproduce the per-field grouped
    path exactly — periodic xyz on the (2,2,2) mesh exercises lane-active
    exchange plus cross-dim corner/edge propagation through the stacked
    pending-plane patches."""
    igg.init_global_grid(8, 8, 8, periodx=1, periody=1, periodz=1,
                         quiet=True)
    # Fresh fields per call (update_halo donates); the seeded generator
    # reproduces identical values.
    ref = _with_stacked(False, _f64_fields(nfields))
    out = _with_stacked(True, _f64_fields(nfields))
    for k, (a, b) in enumerate(zip(ref, out)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f"field {k}")
    igg.finalize_global_grid()


def test_stacked64_update_open_boundaries_and_mixed_group():
    """Open boundaries (stale no-write planes at edge devices) plus a
    group mix: three same-shaped f64 fields (stacked) and one
    staggered-shape f64 field (per-field path — update_halo requires one
    dtype per call, so shape is the mixing axis) — routing must not
    disturb results or ordering."""
    igg.init_global_grid(8, 8, 8, periodx=1, quiet=True)  # y/z open

    def mk():
        odd = igg.zeros((9, 8, 8), dtype=np.float64) + 5.0  # x-staggered
        return (*_f64_fields(3), odd)

    ref = _with_stacked(False, mk())
    out = _with_stacked(True, mk())
    for k, (a, b) in enumerate(zip(ref, out)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f"field {k}")
    igg.finalize_global_grid()


def test_stacked64_path_engages(monkeypatch):
    """The seam really routes >=2 same-shaped lane-active f64 fields
    through the stacked program (and leaves single fields on the
    per-field path)."""
    calls = []
    orig = halo._stacked_lane64_update

    def spy(blocks, dims, grid):
        calls.append(len(blocks))
        return orig(blocks, dims, grid)

    monkeypatch.setattr(halo, "_stacked_lane64_update", spy)
    igg.init_global_grid(8, 8, 8, periodx=1, periody=1, periodz=1,
                         quiet=True)
    _with_stacked(True, _f64_fields(2))
    assert calls == [2]
    calls.clear()
    _with_stacked(True, _f64_fields(1))
    assert calls == []
    calls.clear()
    _with_stacked(False, _f64_fields(2))   # seam off: per-field path
    assert calls == []
    igg.finalize_global_grid()


def test_gather_memory_cliff_warning_retired():
    """Round 9 retired the one-time allgather memory-cliff UserWarning:
    the multi-host fetch is now the root-biased chunked slab path (no
    `process_allgather` anywhere in igg.gather, non-root host memory
    O(slab)), with a one-shot DEBUG log in its place — and a plain gather
    emits no warning at all."""
    import importlib
    import inspect

    gather = importlib.import_module("igg.gather")  # igg.gather the
    # attribute is the function; we need the module

    assert not hasattr(gather, "_warned_allgather")      # flag retired
    # the allgather fallback is gone: nothing in igg.gather even imports
    # the multihost_utils module it lived in (docstrings may MENTION it)
    assert "multihost_utils" not in inspect.getsource(gather)
    assert hasattr(gather, "_fetch_multihost")           # the replacement
    assert hasattr(gather, "_logged_multihost")          # debug-log guard

    igg.init_global_grid(6, 6, 6, quiet=True)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        out = igg.gather(igg.zeros((6, 6, 6)))
    assert out.shape == (12, 12, 12)
    igg.finalize_global_grid()


def test_checkpoint_flat_fallback_logs_debug_not_warning(tmp_path,
                                                         monkeypatch,
                                                         caplog):
    """Round 9: the multi-controller flat-.npz save no longer warns about
    a memory cliff (root-biased fetch keeps non-root memory O(local)); it
    logs ONE debug line naming the sharded alternative."""
    import logging

    import jax
    from jax.experimental import multihost_utils

    from igg import checkpoint

    igg.init_global_grid(4, 4, 4, quiet=True)
    A = igg.zeros((4, 4, 4), dtype=np.float32)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(multihost_utils, "sync_global_devices",
                        lambda tag: None)
    assert not hasattr(checkpoint, "_warned_ckpt_cliff")   # flag retired
    monkeypatch.setattr(checkpoint, "_logged_flat_fallback", False)
    with warnings.catch_warnings():
        warnings.simplefilter("error")          # no UserWarning anymore
        with caplog.at_level(logging.DEBUG, logger="igg.checkpoint"):
            igg.save_checkpoint(tmp_path / "c.npz", T=A)
    assert any("save_checkpoint_sharded" in r.getMessage()
               for r in caplog.records)
    caplog.clear()
    with caplog.at_level(logging.DEBUG, logger="igg.checkpoint"):
        igg.save_checkpoint(tmp_path / "c2.npz", T=A)   # one-shot: silent
    assert not [r for r in caplog.records
                if "save_checkpoint_sharded" in r.getMessage()]
    igg.finalize_global_grid()
