"""Model tests: diffusion3d physics + the fused Pallas kernel (interpret
mode on CPU) against the portable shard_map/XLA path."""

import numpy as np
import pytest

import igg
from igg.models import diffusion3d as d3


def test_decomposition_invariance():
    """The framework's core promise: same global physics on 8 devices as on
    1 (the multi-device analog of the reference's transparently-scaling
    tests, `/root/reference/test/test_update_halo.jl:1-3`)."""
    results = {}
    for tag, kw in [("multi", {}),
                    ("single", dict(dimx=1, dimy=1, dimz=1))]:
        nx = 6 if tag == "multi" else 10  # same global size (open bnds)
        igg.init_global_grid(nx, nx, nx, quiet=True, **kw)
        params = d3.Params()
        T, Cp = d3.init_fields(params, dtype=np.float64)
        step = d3.make_step(params)
        for _ in range(10):
            T = step(T, Cp)
        results[tag] = igg.gather_interior(T)
        igg.finalize_global_grid()
    assert results["multi"].shape == results["single"].shape
    np.testing.assert_allclose(results["multi"], results["single"],
                               rtol=0, atol=1e-12)


def test_multi_step_matches_single_steps():
    igg.init_global_grid(6, 6, 6, periodx=1, periody=1, periodz=1, quiet=True)
    params = d3.Params()
    T1, Cp = d3.init_fields(params, dtype=np.float64)
    T2 = T1
    step = d3.make_step(params, donate=False)
    steps5 = d3.make_multi_step(5, params, donate=False)
    for _ in range(5):
        T1 = step(T1, Cp)
    T2 = steps5(T2, Cp)
    np.testing.assert_allclose(np.array(T1), np.array(T2), atol=1e-12)


def test_pallas_kernel_interpret_matches_xla_path():
    """The fused kernel (interpret mode, exercisable without TPU) must match
    the portable path bit-for-bit up to f32 reassociation (1-device grid,
    fully periodic — the configuration where hide_communication semantics
    coincide exactly with the plain sequential composition)."""
    from igg.ops import fused_diffusion_step, pallas_supported

    igg.init_global_grid(8, 16, 128, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    params = d3.Params(lx=4.0, ly=8.0, lz=60.0)
    T, Cp = d3.init_fields(params, dtype=np.float32)
    assert pallas_supported(igg.get_global_grid(), T)
    dx, dy, dz = params.spacing()
    dt = params.timestep()

    step = d3.make_step(params, donate=False, use_pallas=False)
    Tx = step(T, Cp)
    Tp = fused_diffusion_step(T, Cp, dx=dx, dy=dy, dz=dz, dt=dt,
                              lam=params.lam, bx=4, interpret=True)
    np.testing.assert_allclose(np.array(Tp), np.array(Tx), rtol=2e-6,
                               atol=2e-5)


def test_pallas_sharded_mesh_periodic_matches_xla_path():
    """VERDICT round-1 item 2: the fused Pallas step on a SHARDED mesh (8
    CPU devices, interpret mode) must reproduce the portable shard_map/XLA
    path.  Fully periodic, so the overlap-style exchange is bit-equivalent
    to the sequential composition."""
    igg.init_global_grid(8, 8, 128, periodx=1, periody=1, periodz=1,
                         quiet=True)
    assert igg.get_global_grid().nprocs == 8
    params = d3.Params(lx=4.0, ly=4.0, lz=60.0)
    T, Cp = d3.init_fields(params, dtype=np.float32)

    xla = d3.make_step(params, donate=False, use_pallas=False)
    pal = d3.make_step(params, donate=False, use_pallas=True,
                       pallas_interpret=True)
    Tx, Tp = T, T
    for _ in range(3):
        Tx = xla(Tx, Cp)
        Tp = pal(Tp, Cp)
    np.testing.assert_allclose(np.array(Tp), np.array(Tx), rtol=2e-6,
                               atol=2e-5)


def test_pallas_sharded_mesh_open_boundaries_matches_overlap_path():
    """Open boundaries on a sharded mesh: the fused step has
    hide_communication semantics, so it must match the overlap=True XLA
    path (including the stale-halo no-write behavior at edge devices)."""
    igg.init_global_grid(8, 8, 128, quiet=True)  # open bnds, 8 devices
    params = d3.Params(lx=4.0, ly=4.0, lz=60.0)
    T, Cp = d3.init_fields(params, dtype=np.float32)

    over = d3.make_step(params, donate=False, use_pallas=False, overlap=True)
    pal = d3.make_step(params, donate=False, use_pallas=True,
                       pallas_interpret=True)
    To, Tp = T, T
    for _ in range(3):
        To = over(To, Cp)
        Tp = pal(Tp, Cp)
    np.testing.assert_allclose(np.array(Tp), np.array(To), rtol=2e-6,
                               atol=2e-5)


def test_pallas_slab_carry_multi_step_matches_xla_path():
    """The slab-carry steady state (kernel-emitted boundary slabs feeding the
    next iteration's send planes, `igg.ops.fused_diffusion_steps`) — only
    n_inner > 1 exercises iterations whose slabs came from the kernel, on
    both periodic and open-boundary sharded meshes."""
    for periods in (dict(periodx=1, periody=1, periodz=1), {}):
        igg.init_global_grid(8, 8, 128, quiet=True, **periods)
        params = d3.Params(lx=4.0, ly=4.0, lz=60.0)
        T, Cp = d3.init_fields(params, dtype=np.float32)

        ref = d3.make_multi_step(4, params, donate=False, use_pallas=False,
                                 overlap=True)
        pal = d3.make_multi_step(4, params, donate=False, use_pallas=True,
                                 pallas_interpret=True)
        np.testing.assert_allclose(np.array(pal(T, Cp)),
                                   np.array(ref(T, Cp)),
                                   rtol=2e-6, atol=2e-5)
        igg.finalize_global_grid()


def test_pallas_mixed_wrap_meshes_match_overlap_path():
    """Per-dimension halo modes: dims with a single periodic device are
    handled by in-VMEM wrap (no plane exchange), mixed with exchanged
    dims — the practical 1-D/2-D decompositions `(N,1,1)`/`(N,M,1)`.
    Must match the overlap-semantics XLA path on the 8-device CPU mesh."""
    configs = [
        # (N,M,1): z wrapped, x/y exchanged; mixed periodicity on x.
        dict(dimx=4, dimy=2, dimz=1, periodz=1, periodx=1),
        # (N,1,1): y and z wrapped, only x exchanged; open x boundary.
        dict(dimx=8, dimy=1, dimz=1, periody=1, periodz=1),
        # (1,M,1): x self-swapped, y exchanged, z wrapped.
        dict(dimx=1, dimy=8, dimz=1, periodx=1, periody=1, periodz=1),
    ]
    for kw in configs:
        igg.init_global_grid(8, 8, 128, quiet=True, **kw)
        params = d3.Params(lx=4.0, ly=4.0, lz=60.0)
        T, Cp = d3.init_fields(params, dtype=np.float32)
        ref = d3.make_multi_step(3, params, donate=False, use_pallas=False,
                                 overlap=True)
        pal = d3.make_multi_step(3, params, donate=False, use_pallas=True,
                                 pallas_interpret=True)
        np.testing.assert_allclose(
            np.array(pal(T, Cp)), np.array(ref(T, Cp)), rtol=2e-6,
            atol=2e-5, err_msg=str(kw))
        igg.finalize_global_grid()


def test_pallas_gate_rejects_unsupported():
    igg.init_global_grid(6, 6, 6, quiet=True)  # local block too small
    params = d3.Params()
    T, Cp = d3.init_fields(params, dtype=np.float32)
    with pytest.raises(igg.GridError, match="Pallas"):
        d3.make_step(params, use_pallas=True)(T, Cp)


def test_energy_conservation_periodic():
    igg.init_global_grid(6, 6, 6, periodx=1, periody=1, periodz=1, quiet=True)
    params = d3.Params()
    T, Cp = d3.init_fields(params, dtype=np.float64)
    # conservation of cp*T (the conserved quantity of the flux form)
    e0 = float(np.sum(igg.gather_interior(Cp * T)))
    step = d3.make_step(params)
    for _ in range(20):
        T = step(T, Cp)
    e1 = float(np.sum(igg.gather_interior(Cp * T)))
    assert abs(e1 - e0) / abs(e0) < 1e-13


def test_interior_add_matches_at_add():
    """igg.ops.interior_add must be value-equivalent to `.at[interior].add`
    for plain and per-axis (staggered) pad widths."""
    import jax.numpy as jnp

    from igg.ops import interior_add

    igg.init_global_grid(6, 6, 6, quiet=True)
    rng = np.random.default_rng(0)
    A = jnp.asarray(rng.standard_normal((6, 7, 6)))
    d = jnp.asarray(rng.standard_normal((4, 5, 4)))
    np.testing.assert_array_equal(
        np.asarray(interior_add(A, d)),
        np.asarray(A.at[1:-1, 1:-1, 1:-1].add(d)))
    # staggered 2-D: pad only dim 0
    B = jnp.asarray(rng.standard_normal((7, 6)))
    e = jnp.asarray(rng.standard_normal((5, 6)))
    np.testing.assert_array_equal(
        np.asarray(interior_add(B, e, ((1, 1), (0, 0)))),
        np.asarray(B.at[1:-1, :].add(e)))


def test_stokes_trapezoid_dispatch_admission():
    """The Stokes chunk-tier dispatch contract on make_iteration:
    trapezoid='auto' admits a K on a supported grid, trapezoid=True
    raises the requirement string where no K is admissible, and
    trapezoid=True with use_pallas=False is contradictory (the chunk
    tier rides the fused kernel).  Full equivalence coverage lives in
    tests/test_stokes_trapezoid.py."""
    from igg.models import stokes3d
    from igg.ops import fit_stokes_K, stokes_trapezoid_supported

    igg.init_global_grid(16, 16, 128, dimx=8, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1,
                         overlapx=3, overlapy=3, overlapz=3, quiet=True)
    grid = igg.get_global_grid()
    assert stokes_trapezoid_supported(grid, (16, 16, 128), 4, 4,
                                      np.float32, interpret=True)
    assert fit_stokes_K(grid, (16, 16, 128), 8, np.float32,
                        interpret=True) == 4
    with pytest.raises(igg.GridError, match="chunk tier"):
        stokes3d.make_iteration(stokes3d.Params(), use_pallas=False,
                                trapezoid=True)
    # n_inner=1: no warm-up + chunk possible for any K.
    params = stokes3d.Params(lx=4.0, ly=4.0, lz=4.0)
    it = stokes3d.make_iteration(params, donate=False, use_pallas=True,
                                 pallas_interpret=True, n_inner=1,
                                 trapezoid=True)
    fields = stokes3d.init_fields(params, dtype=np.float32)
    with pytest.raises(igg.GridError, match="chunk tier"):
        it(*fields)
    igg.finalize_global_grid()
