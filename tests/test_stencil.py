"""igg.stencil — the define-your-own-physics frontend.

Three stories, each pinned on the 8-device CPU mesh:

1. **The wave2d mirror is the hand-written module.**  The spec in
   `igg.stencil.library.wave2d_spec` mirrors `igg/models/wave2d.py`
   expression-for-expression; the generated XLA truth and Mosaic tiers
   must be BITWISE the hand ladder's on periodic, open, and mixed
   meshes, and the generated chunk tier bitwise the composition on
   periodic meshes (open-dim chunks — a rung the hand ladder refuses —
   are held to the repo's chunk tolerance, rel < 2e-5 of field scale).
2. **The analyzer derives what the trapezoid modules hand-derive.**
   Read radii, the chunk margin E (the exact recurrence shows the
   hand-written `E = 2K` was conservative), per-dim freeze sets, the
   perf accesses count — and every refusal (unsupported BC, oversized
   read radius, f64-on-Mosaic) surfaces as a structured Admission.
3. **Shallow water is pure frontend input** with the full production
   surface: ladder dispatch, verify-on-first-use quarantine of a
   chaos-corrupted generated kernel with bit-exact XLA fallback under
   `run_resilient`, ensemble membership, halo agreement on the
   staggered fields, and perf/autotune registration.
"""

import numpy as np
import pytest

import igg
from igg import stencil
from igg.models import shallow_water as sw
from igg.models import wave2d

from helpers import assert_halo_agreement


def _wave_setup(dtype=np.float32):
    params = wave2d.Params()
    state0 = wave2d.init_fields(params, dtype=dtype)
    return params, state0, stencil.wave2d_coeffs(params)


# ---------------------------------------------------------------------------
# Spec / algebra validation
# ---------------------------------------------------------------------------

def test_spec_validation_errors():
    F = stencil.Field("F", stagger=(0, 0))
    G = stencil.Field("G", stagger=(0, 0))
    with pytest.raises(igg.GridError, match="undeclared field"):
        stencil.StencilSpec("s", fields=[F],
                            updates=[stencil.Update(F, G[0, 0])])
    with pytest.raises(igg.GridError, match="stagger"):
        stencil.Field("bad", stagger=(2, 0))
    with pytest.raises(igg.GridError, match="1-D offset"):
        F.shift(1)
    with pytest.raises(igg.GridError, match="no updates"):
        stencil.StencilSpec("s", fields=[F], updates=[])
    with pytest.raises(igg.GridError, match="twice"):
        stencil.StencilSpec("s", fields=[F], updates=[
            stencil.Update(F, F[0, 0]), stencil.Update(F, F[0, 0])])
    spec = stencil.StencilSpec("s", fields=[F],
                               updates=[stencil.Update(F, F[0, 0],
                                                       mode="assign")],
                               params=[stencil.Param("a")])
    with pytest.raises(igg.GridError, match="no value"):
        spec.coeffs()
    with pytest.raises(igg.GridError, match="unknown coeffs"):
        spec.coeffs({"a": 1.0, "zz": 2.0})


def test_eq_ne_are_traced_comparisons():
    """`F == x` must build a mask, not a host bool (a bool would
    constant-fold the where on every rung — silently wrong physics the
    verify guard could never catch, since the truth rung would be
    equally wrong)."""
    from igg.stencil.spec import BinOp

    F = stencil.Field("F", stagger=(0, 0))
    e = F[0, 0] == 0
    assert isinstance(e, BinOp) and e.op == "eq"
    n = F[0, 0] != 0
    assert isinstance(n, BinOp) and n.op == "ne"
    # identity hash survives the traced __eq__ (specs key caches by it)
    assert len({F, stencil.Param("p")}) == 2


def test_where_mask_lowers():
    """The where/comparison algebra: a clamped relaxation spec runs and
    clamps (value-level check of the generated XLA composition)."""
    F = stencil.Field("F", stagger=(0, 0))
    r = stencil.Param("r", default=0.25)
    lap = (F[-1, 0] + F[1, 0] + F[0, -1] + F[0, 1] - 4.0 * F[0, 0])
    expr = stencil.where(F[0, 0] > 0.5, 0.0 * F[0, 0], r * lap)
    spec = stencil.StencilSpec("clamped", fields=[F],
                               updates=[stencil.Update(F, expr,
                                                       pad=((1, 1),
                                                            (1, 1)))],
                               params=[r])
    igg.init_global_grid(6, 6, 1, periodx=1, periody=1, quiet=True)
    A = igg.update_halo(igg.zeros((6, 6)) + 0.6)
    step = stencil.compile(spec, donate=False, use_pallas=False)
    (out,) = step(A)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(A))


# ---------------------------------------------------------------------------
# The analyzer
# ---------------------------------------------------------------------------

def test_analyzer_wave2d_facts():
    a = stencil.analyze(stencil.wave2d_spec())
    assert a.halo_radius == (1, 1)
    assert a.accesses == 6         # reads P,Vx,Vy + writes P,Vx,Vy
    # Per-dim freeze sets: each face field is no-write only along its
    # staggered dim (P's computed boundary IS its value).
    assert a.freeze == {0: (1,), 1: (2,)}
    # The exact margin recurrence: the coupled chain loses ONE row of
    # validity per side per step (the hand-derived wave2d E=2K is 2x
    # conservative).
    assert [a.margin_after(K) for K in (1, 2, 4, 8)] == [1, 2, 4, 8]
    assert a.open_chunk_ok(4)


def test_analyzer_margin_tightness_empirical(eight_devices):
    """E = margin_after(K) is exactly tight: one row less and the chunk
    evolution serves stale cells (rel error far beyond tolerance)."""
    from igg.stencil.analyze import Analysis

    orig = Analysis.margin_after
    igg.init_global_grid(16, 16, 1, periodx=1, periody=1, quiet=True)
    params, state0, cf = _wave_setup()
    spec = stencil.wave2d_spec()
    ref = wave2d.make_step(params, donate=False, n_inner=5,
                           use_pallas=False)(*state0)
    try:
        Analysis.margin_after = lambda self, K: max(1, orig(self, K) - 1)
        out = stencil.compile(spec, coeffs=cf, donate=False, n_inner=5,
                              use_pallas=True, pallas_interpret=True,
                              chunk=True, K=4)(*state0)
    finally:
        Analysis.margin_after = orig
    rel = max(
        float(np.abs(np.asarray(r, np.float64)
                     - np.asarray(o, np.float64)).max()
              / (np.abs(np.asarray(r, np.float64)).max() + 1e-30))
        for r, o in zip(ref, out))
    assert rel > 1e-6, rel


def test_analyzer_open_recurrence_refuses_self_negative_assign():
    """An assign field reading ITSELF at a negative offset cannot keep a
    valid computed boundary (its boundary row would read shoulder
    garbage) — the boundary-validity recurrence must refuse open
    chunks for it."""
    F = stencil.Field("F", stagger=(0, 0))
    spec = stencil.StencilSpec(
        "drift", fields=[F],
        updates=[stencil.Update(F, F[-1, 0], mode="assign")])
    a = stencil.analyze(spec)
    assert not a.open_chunk_ok(2)


# ---------------------------------------------------------------------------
# Gate matrix: every analyzer refusal is a structured Admission
# ---------------------------------------------------------------------------

def test_gate_unsupported_bc():
    F = stencil.Field("F", stagger=(0, 0))
    spec = stencil.StencilSpec(
        "s", fields=[F], bc=("reflect", "periodic"),
        updates=[stencil.Update(F, F[0, 0], mode="assign")])
    adm = stencil.admissible(spec)
    assert not adm and "unsupported boundary condition" in adm.reason
    igg.init_global_grid(6, 6, 1, periodx=1, periody=1, quiet=True)
    with pytest.raises(igg.GridError, match="unsupported boundary"):
        stencil.compile(spec)


def test_gate_bc_grid_mismatch():
    F = stencil.Field("F", stagger=(0, 0))
    spec = stencil.StencilSpec(
        "s", fields=[F], bc=("periodic", "any"),
        updates=[stencil.Update(F, F[0, 0], mode="assign")])
    igg.init_global_grid(6, 6, 1, quiet=True)    # all open
    adm = stencil.admissible(spec)
    assert not adm and "requires a periodic dim 0" in adm.reason


def test_gate_oversized_read_radius():
    F = stencil.Field("F", stagger=(0, 0))
    spec = stencil.StencilSpec(
        "wide", fields=[F],
        updates=[stencil.Update(F, F[-2, 0] + F[2, 0],
                                pad=((2, 2), (0, 0)))])
    igg.init_global_grid(6, 6, 1, periodx=1, periody=1, quiet=True)
    adm = stencil.admissible(spec)
    assert not adm and "oversized read radius" in adm.reason
    assert "overlap >= 3" in adm.reason
    with pytest.raises(igg.GridError, match="oversized read radius"):
        stencil.compile(spec)
    # ... and an overlap-3 grid admits it.
    igg.finalize_global_grid()
    igg.init_global_grid(6, 6, 1, periodx=1, periody=1,
                         overlapx=3, overlapy=3, quiet=True)
    assert stencil.admissible(spec)


def test_gate_read_outside_write_region():
    """A read reaching below the write-region origin (or past the
    source's top) refuses with a structured reason instead of dying in
    tracing with an opaque empty-slice shape error."""
    F = stencil.Field("F", stagger=(0, 0))
    spec = stencil.StencilSpec(
        "drift", fields=[F],
        updates=[stencil.Update(F, F[-1, 0], mode="assign")])
    adm = stencil.admissible(spec)
    assert not adm and "outside the source array" in adm.reason
    assert "[0, 0]" in adm.reason     # assign: offsets must be 0 here
    igg.init_global_grid(6, 6, 1, periodx=1, periody=1, quiet=True)
    with pytest.raises(igg.GridError, match="outside the source array"):
        stencil.compile(spec)
    # ...while the pad of an 'add' update widens the legal range: the
    # wave2d velocity read P[-1, 0] under pad ((1,1),(0,0)) admits.
    assert stencil.admissible(stencil.wave2d_spec())


def test_gate_f64_refuses_mosaic_serves_truth(eight_devices):
    igg.init_global_grid(8, 8, 1, periodx=1, periody=1, quiet=True)
    params, _, cf = _wave_setup()
    state64 = wave2d.init_fields(params, dtype=np.float64)
    step = stencil.compile(stencil.wave2d_spec(), coeffs=cf, donate=False,
                           use_pallas="auto", pallas_interpret=True)
    out = step(*state64)
    assert igg.degrade.active().get("wave2d_spec") == "wave2d_spec.xla"
    assert "float64" in igg.degrade.admission_log().get(
        "wave2d_spec.mosaic", "")
    assert all(np.isfinite(np.asarray(o)).all() for o in out)


# ---------------------------------------------------------------------------
# Bit-exactness: spec-compiled wave2d vs the hand-written ladder
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("periods", [(1, 1), (0, 0), (1, 0)],
                         ids=["periodic", "open", "mixed"])
def test_wave2d_spec_matches_hand_ladder(eight_devices, periods):
    """All rungs, 8-device mesh: spec xla == hand xla and spec mosaic ==
    hand mosaic BITWISE; the generated chunk tier is bitwise the
    composition on the periodic mesh (one warm-up + K=4 chunk +
    remainder) and tolerance-equal on open/mixed (the hand ladder has
    no open-chunk rung; 1-ulp f32 cancellation at the frozen
    boundaries)."""
    igg.init_global_grid(8, 8, 1, periodx=periods[0], periody=periods[1],
                         quiet=True)
    params, state0, cf = _wave_setup()
    spec = stencil.wave2d_spec()
    n_inner = 7
    hand_xla = wave2d.make_step(params, donate=False, n_inner=n_inner,
                                use_pallas=False)
    hand_mosaic = wave2d.make_step(params, donate=False, n_inner=n_inner,
                                   use_pallas=True, pallas_interpret=True,
                                   chunk=False)
    ref = hand_xla(*state0)

    s_xla = stencil.compile(spec, coeffs=cf, donate=False,
                            n_inner=n_inner, use_pallas=False)(*state0)
    assert igg.degrade.active()["wave2d_spec"] == "wave2d_spec.xla"
    for r, o, n in zip(ref, s_xla, "P Vx Vy".split()):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(o),
                                      err_msg=f"xla/{n}")

    s_mos = stencil.compile(spec, coeffs=cf, donate=False,
                            n_inner=n_inner, use_pallas=True,
                            pallas_interpret=True, chunk=False)(*state0)
    assert igg.degrade.active()["wave2d_spec"] == "wave2d_spec.mosaic"
    hm = hand_mosaic(*state0)
    for r, o, n in zip(hm, s_mos, "P Vx Vy".split()):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(o),
                                      err_msg=f"mosaic/{n}")

    s_chk = stencil.compile(spec, coeffs=cf, donate=False,
                            n_inner=n_inner, use_pallas=True,
                            pallas_interpret=True, chunk=True,
                            K=4)(*state0)
    assert igg.degrade.active()["wave2d_spec"] == "wave2d_spec.chunk"
    for r, o, n in zip(ref, s_chk, "P Vx Vy".split()):
        if periods == (1, 1):
            np.testing.assert_array_equal(np.asarray(r), np.asarray(o),
                                          err_msg=f"chunk/{n}")
        else:
            a = np.asarray(r, np.float64)
            b = np.asarray(o, np.float64)
            rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-30)
            assert rel < 2e-5, (n, rel)


def test_wave2d_spec_chunk_matches_hand_chunk(eight_devices):
    """Where BOTH ladders serve a chunk rung (periodic, 16^2 blocks so
    the hand tier's E=2K slabs fit), the two chunk tiers agree bitwise
    with the composition and with each other."""
    igg.init_global_grid(16, 16, 1, periodx=1, periody=1, quiet=True)
    params, state0, cf = _wave_setup()
    n_inner = 5
    ref = wave2d.make_step(params, donate=False, n_inner=n_inner,
                           use_pallas=False)(*state0)
    hand = wave2d.make_step(params, donate=False, n_inner=n_inner,
                            use_pallas=True, pallas_interpret=True,
                            chunk=True, K=4)(*state0)
    assert igg.degrade.active()["wave2d"] == "wave2d.chunk"
    spec_c = stencil.compile(stencil.wave2d_spec(), coeffs=cf,
                             donate=False, n_inner=n_inner,
                             use_pallas=True, pallas_interpret=True,
                             chunk=True, K=4)(*state0)
    assert igg.degrade.active()["wave2d_spec"] == "wave2d_spec.chunk"
    for r, h, o, n in zip(ref, hand, spec_c, "P Vx Vy".split()):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(h),
                                      err_msg=f"hand/{n}")
        np.testing.assert_array_equal(np.asarray(r), np.asarray(o),
                                      err_msg=f"spec/{n}")


def test_spec_halo_agreement_staggered(eight_devices):
    """Post-step halo agreement on every spec-compiled staggered field
    (the overlap cells equal the owning neighbor's interior — the
    invariant verify-on-first-use leans on)."""
    igg.init_global_grid(8, 8, 1, periodx=1, periody=1, quiet=True)
    p = sw.Params()
    state = sw.init_fields(p)
    step = sw.make_step(p, donate=False, use_pallas=True,
                        pallas_interpret=True)
    for _ in range(3):
        state = step(*state)
    for a, ls in zip(state, ((8, 8), (9, 8), (8, 9))):
        assert_halo_agreement(np.asarray(a), ls)


@pytest.mark.parametrize("periods", [(1, 1, 1), (0, 0, 0)],
                         ids=["periodic", "open"])
def test_rank3_spec_matches_hand_composition(eight_devices, periods):
    """The frontend is not 2-D-only: a 3-D radius-1 relaxation spec is
    bitwise the hand-written local-step composition on the (2,2,2)
    mesh, on every rung that admits."""
    from igg.ops import interior_add

    igg.init_global_grid(6, 6, 6, periodx=periods[0], periody=periods[1],
                         periodz=periods[2], quiet=True)
    T = stencil.Field("T", stagger=(0, 0, 0))
    r = stencil.Param("r", default=0.1)
    lap = (T[-1, 0, 0] + T[1, 0, 0] + T[0, -1, 0] + T[0, 1, 0]
           + T[0, 0, -1] + T[0, 0, 1] - 6.0 * T[0, 0, 0])
    spec = stencil.StencilSpec(
        "relax3d", fields=[T], params=[r],
        updates=[stencil.Update(T, r * lap, pad=((1, 1),) * 3)])

    def local_step(A):
        lap = (A[:-2, 1:-1, 1:-1] + A[2:, 1:-1, 1:-1]
               + A[1:-1, :-2, 1:-1] + A[1:-1, 2:, 1:-1]
               + A[1:-1, 1:-1, :-2] + A[1:-1, 1:-1, 2:]
               - 6.0 * A[1:-1, 1:-1, 1:-1])
        return igg.update_halo_local(interior_add(A, 0.1 * lap))

    import numpy as _np
    rng = _np.random.default_rng(7)
    A0 = igg.update_halo(igg.from_local_blocks(
        lambda c, ls: rng.standard_normal(ls), (6, 6, 6),
        dtype=np.float32))
    hand = igg.sharded(lambda A: __import__("jax").lax.fori_loop(
        0, 5, lambda _, S: local_step(S), A))
    ref = hand(A0)
    for kw, name in ((dict(use_pallas=False), "xla"),
                     (dict(use_pallas=True, pallas_interpret=True,
                           chunk=False), "mosaic")):
        step = stencil.compile(spec, donate=False, n_inner=5, **kw)
        (out,) = step(A0)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(out),
                                      err_msg=name)
    assert igg.degrade.active()["relax3d"] == "relax3d.mosaic"


# ---------------------------------------------------------------------------
# Shallow water: the BASELINE family as pure frontend input
# ---------------------------------------------------------------------------

def test_shallow_water_decomposition_invariance(eight_devices):
    def run(nx, ny, nt, **kw):
        igg.init_global_grid(nx, ny, 1, periodx=1, periody=1, quiet=True,
                             **kw)
        p = sw.Params()
        state = sw.init_fields(p, dtype=np.float64)
        step = sw.make_step(p, donate=False)
        for _ in range(nt):
            state = step(*state)
        out = tuple(np.asarray(igg.gather_interior(a)) for a in state)
        igg.finalize_global_grid()
        return out

    multi = run(6, 6, 20)                       # (4,2,1) decomposition
    single = run(18, 10, 20, dimx=1, dimy=1, dimz=1)
    for m, s, name in zip(multi, single, "h hu hv".split()):
        assert m.shape == s.shape, name
        np.testing.assert_allclose(m, s, atol=1e-12, err_msg=name)


def test_shallow_water_mass_conserved_and_tiers(eight_devices):
    igg.init_global_grid(8, 8, 1, periodx=1, periody=1, quiet=True)
    p = sw.Params()
    state = sw.init_fields(p)
    mass0 = float(np.sum(np.asarray(igg.gather_interior(state[0]),
                                    np.float64)))
    step = sw.make_step(p, donate=False, use_pallas=True,
                        pallas_interpret=True)
    for _ in range(30):
        state = step(*state)
    assert igg.degrade.active()["shallow_water"] == "shallow_water.mosaic"
    mass1 = float(np.sum(np.asarray(igg.gather_interior(state[0]),
                                    np.float64)))
    assert abs(mass1 - mass0) / abs(mass0) < 1e-6   # periodic continuity
    assert np.isfinite(np.asarray(state[0])).all()
    # chunk rung serves too, tolerance-equal to the truth
    ref = sw.make_step(p, donate=False, n_inner=5,
                       use_pallas=False)(*sw.init_fields(p))
    chk = sw.make_step(p, donate=False, n_inner=5, use_pallas=True,
                       pallas_interpret=True, chunk=True,
                       K=4)(*sw.init_fields(p))
    assert igg.degrade.active()["shallow_water"] == "shallow_water.chunk"
    for r, o, n in zip(ref, chk, "h hu hv".split()):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(o),
                                      err_msg=n)


def test_shallow_water_friction_damps(eight_devices):
    """The cf friction term (a self-read in an add update — algebra
    beyond the wave2d mirror) dissipates energy."""
    igg.init_global_grid(8, 8, 1, periodx=1, periody=1, quiet=True)

    def energy(params, nt=40):
        state = sw.init_fields(params, dtype=np.float64)
        step = sw.make_step(params, donate=False)
        for _ in range(nt):
            state = step(*state)
        return sum(float(np.sum(np.asarray(a, np.float64) ** 2))
                   for a in state)

    free = energy(sw.Params())
    damped = energy(sw.Params(cf=0.5))
    assert damped < free


def test_shallow_water_resilient_chaos_quarantine(eight_devices, tmp_path):
    """The acceptance loop: run_resilient + chaos-corrupted GENERATED
    mosaic kernel -> verify-on-first-use refusal -> quarantine -> the
    run finishes bit-exact to the generated XLA truth with zero
    recovery code."""
    igg.init_global_grid(8, 8, 1, periodx=1, periody=1, quiet=True)
    p = sw.Params()
    h, hu, hv = sw.init_fields(p)
    ref_step = sw.make_step(p, donate=False, use_pallas=False)
    ref = (h, hu, hv)
    for _ in range(10):
        ref = ref_step(*ref)
    igg.degrade.reset()

    def wrap(step):
        def fn(st):
            return dict(zip(("h", "hu", "hv"),
                            step(st["h"], st["hu"], st["hv"])))
        return fn

    with igg.chaos.armed(igg.chaos.kernel_corrupt("shallow_water.mosaic",
                                                  1e3)):
        bad = sw.make_step(p, donate=False, use_pallas="auto",
                           pallas_interpret=True, verify="first_use")
        res = igg.run_resilient(wrap(bad), dict(h=h, hu=hu, hv=hv), 10,
                                checkpoint_dir=str(tmp_path),
                                watch_every=5)
    q = igg.degrade.status()
    assert q["shallow_water.mosaic"].reason == "verify_mismatch"
    assert igg.degrade.active()["shallow_water"] == "shallow_water.xla"
    for r, k in zip(ref, ("h", "hu", "hv")):
        np.testing.assert_array_equal(np.asarray(r),
                                      np.asarray(res.state[k]), err_msg=k)


def test_shallow_water_ensemble_member(eight_devices):
    """Spec-compiled physics as run_ensemble members: the spec's LOCAL
    step (igg.stencil.local_step_fn) vmapped over the member axis."""
    p = sw.Params(lx=10.0, ly=10.0)
    igg.init_global_grid(8, 8, 1, periodx=1, periody=1, quiet=True)
    spec = sw.spec(p)
    local = stencil.local_step_fn(spec, p.coeffs())

    def member_step(st):
        h, hu, hv = local(st["h"], st["hu"], st["hv"])
        return dict(h=h, hu=hu, hv=hv)

    states = []
    for m in range(2):
        h, hu, hv = sw.init_fields(p, dtype=np.float64)
        states.append(dict(h=h * (1.0 + m), hu=hu, hv=hv))
    res = igg.run_ensemble(member_step, states, 5, watch_every=2)
    assert res.steps_done == 5
    assert not res.quarantined
    for m in range(2):
        st = res.member_state(m)
        assert np.isfinite(np.asarray(st["h"])).all()


# ---------------------------------------------------------------------------
# Registration: perf + autotune treat spec families like built-ins
# ---------------------------------------------------------------------------

def test_perf_registration_and_calibrate(eight_devices):
    igg.perf.reset()
    igg.init_global_grid(8, 8, 1, periodx=1, periody=1, quiet=True)
    p = sw.Params()
    sw.make_step(p, donate=False)      # compile registers the family
    reg = igg.perf.registered_families()
    assert "shallow_water" in reg and reg["shallow_water"]["accesses"] == 6
    assert igg.perf.bytes_per_step("shallow_water", "shallow_water.xla",
                                   (8, 8), np.float32) == 6 * 8 * 8 * 4
    # chunk tiers are excluded from the per-step traffic model
    assert igg.perf.bytes_per_step("shallow_water", "shallow_water.chunk",
                                   (8, 8), np.float32) is None
    sec = igg.perf.calibrate("shallow_water", nt=2)
    assert sec > 0
    assert igg.perf.best("shallow_water") is not None


def test_heal_recalibrate_spec_family(eight_devices):
    """The heal loop's drift action measures a spec-defined family
    through the registration hook (no re-anchor fallback: the measured
    seconds come from a fresh calibration dispatch)."""
    igg.perf.reset()
    igg.init_global_grid(8, 8, 1, periodx=1, periody=1, quiet=True)
    sw.make_step(sw.Params(), donate=False)     # registers the family
    sec = igg.heal.recalibrate("shallow_water")
    assert sec is not None and sec > 0
    best = igg.perf.best("shallow_water")
    assert best is not None and "heal" in best["sources"]


def test_autotune_registration_candidates(eight_devices):
    igg.autotune.reset()
    igg.init_global_grid(8, 8, 1, periodx=1, periody=1, quiet=True)
    p = sw.Params()
    sw.make_step(p, donate=False)
    cands = igg.autotune.candidates_for("shallow_water", n_inner=6,
                                        interpret=True)
    tiers = {c["tier"] for c in cands}
    assert {"shallow_water.xla", "shallow_water.mosaic",
            "shallow_water.chunk"} <= tiers
    assert any(c["K"] == 4 for c in cands
               if c["tier"] == "shallow_water.chunk")


def test_unknown_family_errors_name_registry(eight_devices):
    igg.init_global_grid(6, 6, 1, periodx=1, periody=1, quiet=True)
    with pytest.raises(igg.GridError, match="register_family"):
        igg.perf.calibrate("no_such_family")
    with pytest.raises(igg.GridError, match="register_family"):
        igg.autotune.candidates_for("no_such_family")
