"""The performance-observability layer (igg/perf.py): the persistent
perf ledger (record/query/best, versioned JSON persistence, cross-run
merge), watchdog-window attribution via igg.degrade.active_records with
zero extra host syncs, verify-first-use samples, the explicit calibrate
path, roofline + cost-model-drift gauges, and the `python -m igg.perf`
show/merge/compare CLI with the bench regression gate."""

import json
import pathlib

import numpy as np
import pytest

import igg
from igg import perf
from igg import telemetry as tel


@pytest.fixture(autouse=True)
def _clean_perf():
    """The ledger, predictions, metrics, and flight ring are
    process-global by design; isolate every test."""
    perf.reset()
    tel.reset_metrics()
    tel._ring().clear()
    yield
    perf.reset()
    tel.reset_metrics()


CTX = dict(local_shape=(128, 128, 128), dtype="float32", dims=(2, 2, 2),
           backend="tpu", device_kind="TPU v5e")


# ---------------------------------------------------------------------------
# (i) the ledger: record / query / best
# ---------------------------------------------------------------------------

def test_record_aggregates_and_best():
    assert perf.record("diffusion3d", "diffusion3d.mosaic", 2.0,
                       source="bench", **CTX)["count"] == 1
    e = perf.record("diffusion3d", "diffusion3d.mosaic", 1.0,
                    source="watchdog", **CTX)
    assert e["count"] == 2 and e["best_ms"] == 1.0 and e["last_ms"] == 1.0
    assert e["mean_ms"] == pytest.approx(1.5)
    assert e["sources"] == {"bench": 1, "watchdog": 1}
    perf.record("diffusion3d", "diffusion3d.xla", 3.0, **CTX)
    # best() is the autotuner's question: fastest tier for the shape.
    b = perf.best("diffusion3d", local_shape=(128, 128, 128))
    assert b["tier"] == "diffusion3d.mosaic" and b["best_ms"] == 1.0
    # tier/dtype/dims/backend filters narrow it.
    assert perf.best("diffusion3d", tier="diffusion3d.xla")["best_ms"] == 3.0
    assert perf.best("diffusion3d", dtype="bfloat16") is None
    assert perf.best("hm3d") is None
    # query returns best-first.
    q = perf.query("diffusion3d")
    assert [x["tier"] for x in q] == ["diffusion3d.mosaic",
                                     "diffusion3d.xla"]


def test_record_rejects_junk_and_respects_kill_switch(monkeypatch):
    assert perf.record("f", "t", float("nan"), **CTX) is None
    assert perf.record("f", "t", 0.0, **CTX) is None
    assert perf.record("f", "t", "bogus", **CTX) is None
    assert perf.query() == []
    monkeypatch.setenv("IGG_PERF", "0")
    assert not perf.enabled()
    assert perf.record("f", "t", 1.0, **CTX) is None
    assert perf.query() == []


def test_perf_sample_reaches_bus_and_sessions(tmp_path):
    with tel.Telemetry(tmp_path):
        perf.record("diffusion3d", "diffusion3d.mosaic", 2.0, **CTX)
    recs = [json.loads(l) for l in
            (tmp_path / "events_r0.jsonl").read_text().splitlines()]
    samples = [r for r in recs if r["kind"] == "perf_sample"]
    assert samples and samples[0]["payload"]["tier"] == "diffusion3d.mosaic"
    assert samples[0]["payload"]["ms_per_step"] == 2.0
    assert any(r.kind == "perf_sample" for r in tel.flight_recorder())


# ---------------------------------------------------------------------------
# (ii) roofline + cost-model-drift gauges
# ---------------------------------------------------------------------------

def test_roofline_gauges_from_analytic_bytes():
    # diffusion3d: 3 accesses * 128^3 cells * 4 B = 25.166 MB/step; at
    # 2 ms that is ~12.58 GB/s, ~1.54% of the v5e 819 GB/s peak.
    perf.record("diffusion3d", "diffusion3d.mosaic", 2.0, **CTX)
    snap = tel.snapshot()
    gbps = snap['igg_achieved_gbps{family="diffusion3d",'
                'tier="diffusion3d.mosaic"}']["value"]
    assert gbps == pytest.approx(3 * 128 ** 3 * 4 / 2e-3 / 1e9)
    pct = snap['igg_pct_hbm_peak{family="diffusion3d",'
               'tier="diffusion3d.mosaic"}']["value"]
    assert pct == pytest.approx(100 * gbps / 819.0)


def test_roofline_skips_unknown_models():
    assert perf.bytes_per_step("nosuch", "t", (8, 8, 8), "float32") is None
    # trapezoid tiers amortize traffic over K — no per-step model.
    assert perf.bytes_per_step("stokes3d", "stokes3d.trapezoid",
                               (128,) * 3, "float32") is None
    assert perf.bytes_per_step("stokes3d", "stokes3d.mosaic",
                               (128,) * 3, "float32") \
        == 9 * 128 ** 3 * 4
    assert perf.hbm_peak_gbps("cpu") is None
    assert perf.hbm_peak_gbps("TPU v5p") == 2765.0
    assert perf.hbm_peak_gbps("TPU v5 lite") == 819.0
    ctx = dict(CTX, device_kind="cpu")
    perf.record("nosuch", "t", 2.0, **{**ctx, "local_shape": (8, 8, 8)})
    assert not any(k.startswith("igg_achieved_gbps")
                   for k in tel.snapshot())


def test_cost_model_drift_gauge_and_event():
    tol_default = 0.5
    perf.predict("diffusion3d", 0.0021)   # 2.1 ms predicted
    perf.record("diffusion3d", "diffusion3d.mosaic", 2.0, **CTX)
    snap = tel.snapshot()
    rel = snap['igg_cost_model_rel_error{family="diffusion3d"}']["value"]
    assert rel == pytest.approx((2.1 - 2.0) / 2.0)
    assert abs(rel) < tol_default
    assert not [r for r in tel.flight_recorder()
                if r.kind == "cost_model_drift"]
    # Past the threshold: gauge updates AND the drift event fires (once
    # per (family, tier)).
    perf.predict("diffusion3d", 0.010)    # 10 ms predicted vs 2 measured
    perf.record("diffusion3d", "diffusion3d.mosaic", 2.0, **CTX)
    perf.record("diffusion3d", "diffusion3d.mosaic", 2.0, **CTX)
    drifts = [r for r in tel.flight_recorder()
              if r.kind == "cost_model_drift"]
    assert len(drifts) == 1
    assert drifts[0].payload["rel_error"] == pytest.approx(4.0)
    assert drifts[0].payload["tol"] == tol_default


def test_drift_threshold_env_knob(monkeypatch):
    monkeypatch.setenv("IGG_PERF_DRIFT_TOL", "0.01")
    perf.predict("hm3d", 0.00205)
    perf.record("hm3d", "hm3d.mosaic", 2.0, **CTX)
    drifts = [r for r in tel.flight_recorder()
              if r.kind == "cost_model_drift"]
    assert drifts and drifts[0].payload["tol"] == pytest.approx(0.01)


# ---------------------------------------------------------------------------
# (iii) persistence: versioned JSON, merge-on-write, CLI round-trip
# ---------------------------------------------------------------------------

def test_save_load_roundtrip_and_format_guard(tmp_path):
    perf.record("diffusion3d", "diffusion3d.mosaic", 2.0, **CTX)
    p = tmp_path / "ledger.json"
    assert perf.save(p) == p
    doc = json.loads(p.read_text())
    assert doc["format"] == perf.LEDGER_FORMAT
    perf.reset()
    assert perf.load(p) == 1
    assert perf.best("diffusion3d")["best_ms"] == 2.0
    # merge-on-write: a second process's save does not clobber.
    perf.reset()
    perf.record("diffusion3d", "diffusion3d.xla", 5.0, **CTX)
    perf.save(p)
    perf.reset()
    assert perf.load(p) == 2
    # wrong format refuses loudly.
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"format": "igg-perf-ledger-v999",
                               "entries": {}}))
    with pytest.raises(igg.GridError, match="igg-perf-ledger-v1"):
        perf.load(bad)
    with pytest.raises(igg.GridError, match="valid JSON"):
        (tmp_path / "junk.json").write_text("{")
        perf.load(tmp_path / "junk.json")
    with pytest.raises(igg.GridError, match="IGG_PERF_LEDGER"):
        perf.load()


def test_env_ledger_path_and_autosave(tmp_path, monkeypatch):
    target = tmp_path / "auto" / "ledger.json"
    monkeypatch.setenv("IGG_PERF_LEDGER", str(target))
    monkeypatch.setenv("IGG_PERF_SAVE_EVERY", "0")   # save on every record
    assert perf.ledger_path() == target
    perf.record("diffusion3d", "diffusion3d.mosaic", 2.0, **CTX)
    assert target.exists()   # parents created, autosaved
    doc = json.loads(target.read_text())
    assert len(doc["entries"]) == 1


def test_repeated_saves_never_double_count(tmp_path):
    """save() merges only the DELTA since this process's last save to
    the file — re-merging the full in-memory ledger on every autosave
    would inflate count/sum on each cycle (review finding, round 13)."""
    p = tmp_path / "ledger.json"
    perf.record("f", "t", 2.0, **CTX)
    perf.save(p)
    perf.record("f", "t", 4.0, **CTX)
    perf.save(p)
    perf.save(p)   # a save with nothing new is a no-op on the aggregates
    e = next(iter(json.loads(p.read_text())["entries"].values()))
    assert e["count"] == 2
    assert e["sum_ms"] == pytest.approx(6.0)
    assert e["sources"] == {"api": 2}
    # load() credits the loaded amounts to the file's baseline: a
    # load-then-save round trip must not re-merge them either.
    perf.load(p)          # memory now holds 2x (its own + the file's)
    perf.save(p)
    e = next(iter(json.loads(p.read_text())["entries"].values()))
    assert e["count"] == 2 and e["sum_ms"] == pytest.approx(6.0)
    # replace=True redefines memory as the file: still no inflation.
    perf.load(p, replace=True)
    perf.record("f", "t", 10.0, **CTX)
    perf.save(p)
    e = next(iter(json.loads(p.read_text())["entries"].values()))
    assert e["count"] == 3 and e["sum_ms"] == pytest.approx(16.0)


def test_merge_ledgers_combines_aggregates(tmp_path):
    perf.record("f", "t", 2.0, **CTX)
    a = tmp_path / "a.json"
    perf.save(a)
    perf.reset()
    perf.record("f", "t", 1.0, **CTX)
    perf.record("f", "u", 9.0, **CTX)
    b = tmp_path / "b.json"
    perf.save(b)
    rc = perf._main(["merge", str(tmp_path / "m.json"), str(a), str(b)])
    assert rc == 0
    doc = json.loads((tmp_path / "m.json").read_text())
    assert doc["format"] == perf.LEDGER_FORMAT
    assert len(doc["entries"]) == 2
    e = next(v for v in doc["entries"].values() if v["tier"] == "t")
    assert e["count"] == 2 and e["best_ms"] == 1.0
    assert e["sum_ms"] == pytest.approx(3.0)


def test_cli_show(tmp_path, capsys):
    perf.record("diffusion3d", "diffusion3d.mosaic", 2.0, **CTX)
    p = tmp_path / "ledger.json"
    perf.save(p)
    assert perf._main(["show", str(p)]) == 0
    out = capsys.readouterr().out
    assert "diffusion3d.mosaic" in out and "128x128x128" in out
    assert perf._main(["show", str(p), "--family", "nosuch"]) == 0
    assert "mosaic" not in capsys.readouterr().out
    assert perf._main(["show", str(tmp_path / "absent.json")]) == 2
    assert perf._main([]) == 2
    assert perf._main(["frobnicate"]) == 2


# ---------------------------------------------------------------------------
# (iv) the regression gate (compare)
# ---------------------------------------------------------------------------

def _row(metric="m", value=1.0, unit="ms", config=None, backend="cpu",
         device_kind="cpu", smoke=True, **extra):
    return {"metric": metric, "value": value, "unit": unit,
            "config": config or {"n": 64}, "smoke": smoke,
            "provenance": {"backend": backend, "device_kind": device_kind},
            **extra}


def test_compare_value_directions():
    base = [_row("ms_row", 100.0, "ms"),
            _row("gbps_row", 50.0, "GB/s"),
            _row("err_row", 0.05, "relative error (predicted-measured)")]
    # Within tolerance everywhere -> no regressions.
    new = [_row("ms_row", 105.0, "ms"), _row("gbps_row", 48.0, "GB/s"),
           _row("err_row", -0.06, "relative error (predicted-measured)")]
    rep = perf.compare_rows(base, new, tol=0.1)
    assert not rep["failed"] and len(rep["ok"]) == 3
    # Lower-is-better grows, higher-is-better shrinks, |error| grows.
    worse = [_row("ms_row", 120.0, "ms"),
             _row("gbps_row", 40.0, "GB/s"),
             _row("err_row", 0.30, "relative error (predicted-measured)")]
    rep = perf.compare_rows(base, worse, tol=0.1)
    assert rep["failed"] and len(rep["regressions"]) == 3
    # Improvements are reported, never regressions.
    better = [_row("ms_row", 50.0, "ms"), _row("gbps_row", 80.0, "GB/s"),
              _row("err_row", 0.0, "relative error (predicted-measured)")]
    rep = perf.compare_rows(base, better, tol=0.1)
    assert not rep["failed"] and len(rep["improvements"]) == 2


def test_compare_fraction_units_are_higher_is_better():
    """weak_scaling/overlap_schedule rows carry efficiency/overlap
    'fraction' units: shrinking is the regression (review finding)."""
    base = [_row("eff", 0.95, "fraction"),
            _row("ovl", 0.90, "fraction of compute cycles with >=1 "
                              "permute in flight")]
    rep = perf.compare_rows(base, [_row("eff", 0.50, "fraction"),
                                   _row("ovl", 0.91, "fraction of "
                                        "compute cycles with >=1 "
                                        "permute in flight")], tol=0.1)
    assert rep["failed"] and len(rep["regressions"]) == 1
    assert rep["regressions"][0][0][0] == "eff"
    rep = perf.compare_rows(base, [_row("eff", 0.99, "fraction"),
                                   _row("ovl", 0.90, "fraction")],
                            tol=0.1)
    assert not rep["failed"]


def test_compare_pass_rows_gate_on_the_flag():
    base = [_row("contract", 0.03, "%", **{"pass": True})]
    # The value of a contract row is informational: a 10x noise swing on
    # a shared CI host must not flake the gate while "pass" holds...
    rep = perf.compare_rows(base,
                            [_row("contract", 0.4, "%", **{"pass": True})],
                            tol=0.1)
    assert not rep["failed"]
    # ...but the flag flipping false always fails it.
    rep = perf.compare_rows(base,
                            [_row("contract", 0.4, "%",
                                  **{"pass": False})], tol=0.1)
    assert rep["failed"]
    assert "pass" in rep["regressions"][0][1][0]
    # --gate-pass-values opts the value back into the gate.
    rep = perf.compare_rows(base,
                            [_row("contract", 0.4, "%", **{"pass": True})],
                            tol=0.1, gate_pass_values=True)
    assert rep["failed"]


def test_compare_provenance_scoping_and_missing():
    base = [_row("cpu_row", 1.0), _row("tpu_row", 1.0, backend="tpu",
                                       device_kind="TPU v5e", smoke=False)]
    # A new set from a CPU host: the TPU golden is out of scope, not
    # missing — different hosts never gate each other.
    rep = perf.compare_rows(base, [_row("cpu_row", 1.0)], tol=0.1)
    assert not rep["failed"] and len(rep["out_of_scope"]) == 1
    # Same provenance but the row vanished: missing fails the gate...
    rep = perf.compare_rows(base, [_row("other", 1.0)], tol=0.1)
    assert rep["failed"] and len(rep["missing"]) == 1
    # ...unless explicitly allowed.
    rep = perf.compare_rows(base, [_row("other", 1.0)], tol=0.1,
                            allow_missing=True)
    assert not rep["failed"] and len(rep["new_only"]) == 1


def test_compare_cli_paths_and_injected_regression(tmp_path):
    """The ci.sh shape: goldens dir vs results dir, then a synthetic 20%
    slowdown row must flip the exit code at --tol 0.1."""
    g = tmp_path / "goldens"
    r = tmp_path / "results"
    g.mkdir(), r.mkdir()
    (g / "bench.jsonl").write_text(json.dumps(_row("ms_row", 100.0)) + "\n")
    (r / "bench.jsonl").write_text(json.dumps(_row("ms_row", 104.0)) + "\n")
    assert perf._main(["compare", str(g), str(r), "--tol", "0.1"]) == 0
    (r / "bench.jsonl").write_text(json.dumps(_row("ms_row", 120.0)) + "\n")
    assert perf._main(["compare", str(g), str(r), "--tol", "0.1"]) == 1
    # .failed.jsonl postmortem salvage is never read as evidence.
    (r / "bench.jsonl").write_text(json.dumps(_row("ms_row", 104.0)) + "\n")
    (r / "x.failed.jsonl").write_text(json.dumps(_row("ms_row", 999.0))
                                      + "\n")
    assert perf._main(["compare", str(g), str(r), "--tol", "0.1"]) == 0
    assert perf._main(["compare", str(g)]) == 2   # usage
    assert perf._main(["compare", str(tmp_path / "void"), str(r)]) == 2


# ---------------------------------------------------------------------------
# (v) attribution + calibrate on the live grid
# ---------------------------------------------------------------------------

def _grid():
    igg.init_global_grid(8, 8, 128, periodx=1, periody=1, periodz=1,
                         quiet=True)


def test_observe_window_attributes_to_serving_tier():
    from igg.models import diffusion3d as d3

    _grid()
    igg.degrade.reset()
    state = perf.window_state()      # BEFORE the run's dispatches
    params = d3.Params()
    T, Cp = d3.init_fields(params, dtype=np.float32)
    step = d3.make_step(params, donate=False, pallas_interpret=True)
    T = step(T, Cp)
    ctx = perf.sample_context(T)
    assert ctx["local_shape"] == (8, 8, 128)   # the per-device block
    out = perf.observe_window("resilient", 3.0, 10, ctx, state)
    assert len(out) == 1
    e = out[0]
    assert e["family"] == "diffusion3d"
    assert e["tier"] == igg.degrade.active()["diffusion3d"]
    assert e["sources"] == {"watchdog": 1}
    assert tuple(e["local_shape"]) == (8, 8, 128)
    # No dispatch since the last window -> nothing new is attributed (a
    # tier warmed by an unrelated earlier factory is never credited).
    assert perf.observe_window("resilient", 3.0, 10, ctx, state) == []
    igg.degrade.reset()
    igg.finalize_global_grid()


def test_run_resilient_feeds_ledger_via_watchdog(tmp_path, monkeypatch):
    """The acceptance path: a model-backed run on the 8-device mesh
    produces ledger entries for the served (family, tier, shape) that
    answer best(), persisted to the env-configured ledger file."""
    import warnings

    from igg.models import diffusion3d as d3

    monkeypatch.setenv("IGG_PERF_LEDGER", str(tmp_path / "ledger.json"))
    _grid()
    igg.degrade.reset()
    params = d3.Params()
    T0, Cp = d3.init_fields(params, dtype=np.float32)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        step = d3.make_step(params, donate=False, pallas_interpret=True,
                            verify="first_use")
        res = igg.run_resilient(lambda s: {"T": step(s["T"], Cp)},
                                {"T": T0 + 0}, 30, watch_every=10,
                                install_sigterm=False, telemetry=False)
    assert res.steps_done == 30
    serving = igg.degrade.active()["diffusion3d"]
    e = perf.best("diffusion3d", local_shape=(8, 8, 128), tier=serving)
    assert e is not None, perf.query()
    assert "verify_first_use" in e["sources"]
    assert "watchdog" in e["sources"]
    assert perf.save() is not None
    doc = json.loads((tmp_path / "ledger.json").read_text())
    assert any(v["tier"] == serving for v in doc["entries"].values())
    igg.degrade.reset()
    igg.finalize_global_grid()


def test_calibrate_records_and_validates():
    _grid()
    igg.degrade.reset()
    sec = perf.calibrate("diffusion3d", nt=2, warmup=0)
    assert sec > 0
    e = perf.best("diffusion3d")
    assert e is not None and e["sources"] == {"calibrate": 1}
    assert e["tier"] == igg.degrade.active()["diffusion3d"]
    with pytest.raises(igg.GridError, match="unknown family"):
        perf.calibrate("nosuch")
    with pytest.raises(igg.GridError, match="family="):
        perf.calibrate(lambda x: x, (1,))
    with pytest.raises(igg.GridError, match="args="):
        perf.calibrate(lambda x: x, family="f")
    with pytest.raises(igg.GridError, match="nt"):
        perf.calibrate("diffusion3d", nt=0)
    igg.degrade.reset()
    igg.finalize_global_grid()


def test_calibrate_stokes_and_hm3d_families():
    """The other two named-family conveniences (the Stokes iteration's
    Rho pass-through has its own wrapper shape)."""
    igg.init_global_grid(8, 8, 8, periodx=1, periody=1, periodz=1,
                         overlapx=3, overlapy=3, overlapz=3, quiet=True)
    igg.degrade.reset()
    assert perf.calibrate("stokes3d", nt=1, warmup=0) > 0
    igg.finalize_global_grid()
    igg.init_global_grid(8, 8, 8, periodx=1, periody=1, periodz=1,
                         quiet=True)
    assert perf.calibrate("hm3d", nt=1, warmup=0) > 0
    assert {e["family"] for e in perf.query()} == {"stokes3d", "hm3d"}
    igg.degrade.reset()
    igg.finalize_global_grid()


def test_calibrate_explicit_step_callable():
    _grid()
    calls = []

    def fake_step(x):
        calls.append(1)
        return x

    sec = perf.calibrate(fake_step, (np.float32(1.0),), family="custom",
                         tier="custom.xla", nt=2, warmup=0)
    assert sec >= 0 and len(calls) == 2 + 6
    e = perf.best("custom")
    assert e["tier"] == "custom.xla"
    igg.finalize_global_grid()


def test_perf_env_knobs_registered():
    from igg import _env

    for name in ("IGG_PERF", "IGG_PERF_LEDGER", "IGG_PERF_SAVE_EVERY",
                 "IGG_PERF_DRIFT_TOL"):
        assert name in _env._KNOWN, name


# ---------------------------------------------------------------------------
# Round 16: best() tie-breaking + tuning-cache staleness (autotuner prior)
# ---------------------------------------------------------------------------

def test_best_tie_breaking_deterministic():
    """Equal-best samples from different sources must order
    deterministically: higher sample count first, then the freshest
    `updated_wall`, then tier name — so the autotuner's prior is stable
    run to run."""
    import time as _time

    # Same best_ms from two different sources; the second tier gathers
    # more evidence (count 3 vs 1).
    perf.record("f", "f.zeta", 1.0, source="watchdog", **CTX)
    for _ in range(3):
        perf.record("f", "f.alpha", 1.0, source="calibrate", **CTX)
    q = perf.query("f")
    assert [e["tier"] for e in q] == ["f.alpha", "f.zeta"]
    assert perf.best("f")["tier"] == "f.alpha"
    # Equal best AND equal count: the fresher entry wins.
    perf.record("g", "g.old", 2.0, source="bench", **CTX)
    _time.sleep(0.01)
    perf.record("g", "g.new", 2.0, source="autotune", **CTX)
    assert perf.best("g")["tier"] == "g.new"
    # Fully equal aggregates (count, freshness forced identical): the
    # tier NAME is the final deterministic key.
    with perf._lock:
        for k in list(perf._LEDGER):
            if k[0] == "g":
                perf._LEDGER[k]["updated_wall"] = 123.0
                perf._LEDGER[k]["count"] = 1
    assert perf.best("g")["tier"] == "g.new"   # "g.new" < "g.old"


def test_invalidate_evicts_tuning_cache_entries(tmp_path, monkeypatch):
    """The heal-loop interplay on the 8-device mesh: `invalidate()`
    dropping a family's ledger entries must also evict its tuning-cache
    winners (memory and disk), and report the eviction count on the
    `perf_invalidated` bus record."""
    from igg import autotune

    monkeypatch.setenv("IGG_TUNE_CACHE", str(tmp_path / "tune.json"))
    autotune.reset()
    _grid()
    try:
        ctx = perf.sample_context()
        perf.record("diffusion3d", "diffusion3d.mosaic", 1.0,
                    source="autotune", local_shape=(16, 16, 128),
                    dtype="float32", dims=ctx.get("dims"),
                    backend=ctx.get("backend"),
                    device_kind=ctx.get("device_kind"))
        autotune.record_winner(
            "diffusion3d", {"tier": "diffusion3d.mosaic", "K": 8, "bx": 8,
                            "vmem_mb": None, "ms": 1.0},
            local_shape=(16, 16, 128))
        assert autotune.get("diffusion3d",
                            local_shape=(16, 16, 128)) is not None
        n = perf.invalidate("diffusion3d")
        assert n == 1
        assert perf.best("diffusion3d") is None
        assert autotune.get("diffusion3d",
                            local_shape=(16, 16, 128)) is None
        inv = [r for r in tel.flight_recorder()
               if r.kind == "perf_invalidated"]
        assert inv and inv[-1].payload["tune_evicted"] == 1
    finally:
        autotune.reset()
        igg.finalize_global_grid()
