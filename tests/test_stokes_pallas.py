"""Fused Pallas Stokes iteration vs the XLA composition (interpret mode).

The compiled kernel matches the XLA path to ~1e-7 relative on real TPU
(pinned by tests/test_mega_tpu.py::test_stokes_kernel_compiled_matches_xla;
the round-4 mesh-capable rewrite recomputes send/fallback planes from thin
windows, so Mosaic-vs-XLA reassociation differences of a few ulp are
expected).  Interpret mode on CPU executes the same program structure and
must agree to float32 rounding.
"""

import numpy as np
import pytest

import igg
from igg.models import stokes3d


@pytest.fixture
def selfwrap_grid():
    igg.init_global_grid(16, 8, 8, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1,
                         overlapx=3, overlapy=3, overlapz=3, quiet=True)
    yield igg.get_global_grid()
    igg.finalize_global_grid()


def _fields():
    import jax.numpy as jnp

    params = stokes3d.Params()
    P, Vx, Vy, Vz, Rho = stokes3d.init_fields(params, dtype=np.float32)
    mk = lambda A, f, s: f(jnp.arange(A.size, dtype=np.float32)
                           .reshape(A.shape) * s)
    return (mk(P, jnp.sin, 1.0), mk(Vx, jnp.cos, 0.01),
            mk(Vy, jnp.sin, 0.02), mk(Vz, jnp.cos, 0.03), Rho)


def test_supported(selfwrap_grid):
    from igg.ops import stokes_pallas_supported

    import jax
    P = jax.ShapeDtypeStruct((16, 8, 8), np.float32)
    assert stokes_pallas_supported(selfwrap_grid, P)


def test_not_supported_wrong_overlap():
    from igg.ops import stokes_pallas_supported

    import jax
    igg.init_global_grid(16, 8, 8, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    P = jax.ShapeDtypeStruct((16, 8, 8), np.float32)
    assert not stokes_pallas_supported(igg.get_global_grid(), P)
    igg.finalize_global_grid()


def test_supported_open_boundary_and_mesh():
    """Round 4: the kernel is mesh-capable — open boundaries and multi-
    device decompositions are in scope (the exchange engine handles them)."""
    from igg.ops import stokes_pallas_supported

    import jax
    igg.init_global_grid(16, 8, 8, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=0, periodz=1,
                         overlapx=3, overlapy=3, overlapz=3, quiet=True)
    P = jax.ShapeDtypeStruct((16, 8, 8), np.float32)
    assert stokes_pallas_supported(igg.get_global_grid(), P)
    igg.finalize_global_grid()
    igg.init_global_grid(16, 8, 8, overlapx=3, overlapy=3, overlapz=3,
                         quiet=True)   # 8 devices, open boundaries
    assert stokes_pallas_supported(igg.get_global_grid(), P)
    igg.finalize_global_grid()


def test_use_pallas_on_unsupported_grid_raises():
    igg.init_global_grid(16, 8, 8, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)  # ol 2
    params = stokes3d.Params()
    kw = stokes3d._pseudo_steps(params)
    fields = _fields()
    with pytest.raises(igg.GridError, match="fused Stokes"):
        stokes3d.local_iteration(*fields, **kw, use_pallas=True,
                                 pallas_interpret=True)
    igg.finalize_global_grid()


def test_matches_xla_one_iteration(selfwrap_grid):
    params = stokes3d.Params()
    kw = stokes3d._pseudo_steps(params)
    fields = _fields()
    ref = stokes3d.local_iteration(*fields, **kw)
    out = stokes3d.local_iteration(*fields, **kw, use_pallas=True,
                                   pallas_interpret=True)
    for name, a, b in zip(("P", "Vx", "Vy", "Vz"), ref, out):
        a, b = np.asarray(a), np.asarray(b)
        rel = np.max(np.abs(a - b)) / (np.max(np.abs(a)) + 1e-30)
        assert rel < 1e-6, (name, rel)


def test_make_iteration_pallas_through_sharded(selfwrap_grid):
    """The compiled entry (igg.sharded / shard_map + fori_loop): interpret
    kernels under shard_map need the check_vma workaround — this is the path
    the benchmark and driver dryrun use."""
    params = stokes3d.Params()
    it_x = stokes3d.make_iteration(params, n_inner=2, donate=False,
                                   use_pallas=False)
    it_p = stokes3d.make_iteration(params, n_inner=2, donate=False,
                                   use_pallas=True, pallas_interpret=True)
    fields = _fields()
    ref = it_x(*fields)
    out = it_p(*fields)
    for name, a, b in zip(("P", "Vx", "Vy", "Vz"), ref, out):
        a, b = np.asarray(a), np.asarray(b)
        rel = np.max(np.abs(a - b)) / (np.max(np.abs(a)) + 1e-30)
        assert rel < 1e-5, (name, rel)


def test_matches_xla_chained(selfwrap_grid):
    """Five chained iterations: halo invariants carried by the kernel feed
    the next iteration's windows."""
    params = stokes3d.Params()
    kw = stokes3d._pseudo_steps(params)
    fields = _fields()
    r = o = fields[:4]
    Rho = fields[4]
    for _ in range(5):
        r = stokes3d.local_iteration(*r, Rho, **kw)
        o = stokes3d.local_iteration(*o, Rho, **kw, use_pallas=True,
                                     pallas_interpret=True)
    for name, a, b in zip(("P", "Vx", "Vy", "Vz"), r, o):
        a, b = np.asarray(a), np.asarray(b)
        rel = np.max(np.abs(a - b)) / (np.max(np.abs(a)) + 1e-30)
        assert rel < 1e-5, (name, rel)


def _mesh_compare(grid_kw, n_iters=3, tol=2e-5):
    """Shared body: fused kernel (interpret) vs the overlap-semantics XLA
    path on a sharded 8-device CPU mesh."""
    igg.init_global_grid(16, 8, 8, overlapx=3, overlapy=3, overlapz=3,
                         quiet=True, **grid_kw)
    params = stokes3d.Params(lx=4.0, ly=4.0, lz=4.0)
    fields = stokes3d.init_fields(params, dtype=np.float32)
    ref = stokes3d.make_iteration(params, donate=False, use_pallas=False,
                                  overlap=True, n_inner=n_iters)
    pal = stokes3d.make_iteration(params, donate=False, use_pallas=True,
                                  pallas_interpret=True, n_inner=n_iters)
    r = ref(*fields)
    o = pal(*fields)
    for name, a, b in zip(("P", "Vx", "Vy", "Vz"), r, o):
        a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
        rel = np.max(np.abs(a - b)) / (np.max(np.abs(a)) + 1e-30)
        assert rel < tol, (name, rel, grid_kw)
    igg.finalize_global_grid()


def test_mesh_222_periodic_matches_overlap_path():
    """VERDICT round-3 item 1: the fused Stokes iteration on the (2,2,2)
    sharded CPU mesh must reproduce the overlap-semantics XLA path."""
    _mesh_compare(dict(periodx=1, periody=1, periodz=1))


def test_mesh_222_open_matches_overlap_path():
    """Open boundaries: stale-halo no-write at edge devices."""
    _mesh_compare({})


def test_mesh_421_mixed_wrap_matches_overlap_path():
    """(4,2,1) mesh: z wrapped in-VMEM, x/y exchanged, mixed periodicity."""
    _mesh_compare(dict(dimx=4, dimy=2, dimz=1, periodx=1, periodz=1))


def test_mesh_811_matches_overlap_path():
    """(8,1,1): y/z wrapped, only x exchanged, open x boundary."""
    _mesh_compare(dict(dimx=8, dimy=1, dimz=1, periody=1, periodz=1))


def test_decomposition_invariance_open_boundaries():
    """Round-4 regression: the fused iteration on an open-boundary (2,2,2)
    mesh must reproduce the PLAIN single-device physics on the gathered
    interior.  Pins the open-boundary fallback-plane semantics: the
    full-shape pressure update writes the outermost global planes, and the
    fallback must preserve those computed values (slab-computed planes),
    not revert them to pre-iteration values."""
    results = {}
    for tag, kw, local in (("multi", {}, (16, 8, 8)),
                           ("single", dict(dimx=1, dimy=1, dimz=1),
                            (29, 13, 13))):
        igg.init_global_grid(*local, overlapx=3, overlapy=3, overlapz=3,
                             quiet=True, **kw)
        params = stokes3d.Params(lx=4.0, ly=4.0, lz=4.0)
        P, Vx, Vy, Vz, Rho = stokes3d.init_fields(params, dtype=np.float32)
        it = stokes3d.make_iteration(
            params, donate=False, n_inner=2,
            use_pallas=(tag == "multi"),
            pallas_interpret=(tag == "multi"))
        S = (P, Vx, Vy, Vz)
        for _ in range(3):
            S = it(*S, Rho)
        results[tag] = tuple(np.asarray(igg.gather_interior(F), np.float64)
                             for F in S)
        igg.finalize_global_grid()
    for i, name in enumerate(("P", "Vx", "Vy", "Vz")):
        a, b = results["multi"][i], results["single"][i]
        assert a.shape == b.shape, (name, a.shape, b.shape)
        scale = max(np.abs(b).max(), 1e-30)
        assert np.abs(a - b).max() <= 1e-5 * scale, name
