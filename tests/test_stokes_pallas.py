"""Fused Pallas Stokes iteration vs the XLA composition (interpret mode).

The compiled kernel matches the XLA path BITWISE on real TPU (checked in
the benchmark path); interpret mode on CPU executes the same program
structure and must agree to float32 rounding (the x-halo planes are
recomputed from thin windows, so reassociation differences of ~1-2 ulp are
expected — same contract as the diffusion kernel's alias invariant).
"""

import numpy as np
import pytest

import igg
from igg.models import stokes3d


@pytest.fixture
def selfwrap_grid():
    igg.init_global_grid(16, 8, 8, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1,
                         overlapx=3, overlapy=3, overlapz=3, quiet=True)
    yield igg.get_global_grid()
    igg.finalize_global_grid()


def _fields():
    import jax.numpy as jnp

    params = stokes3d.Params()
    P, Vx, Vy, Vz, Rho = stokes3d.init_fields(params, dtype=np.float32)
    mk = lambda A, f, s: f(jnp.arange(A.size, dtype=np.float32)
                           .reshape(A.shape) * s)
    return (mk(P, jnp.sin, 1.0), mk(Vx, jnp.cos, 0.01),
            mk(Vy, jnp.sin, 0.02), mk(Vz, jnp.cos, 0.03), Rho)


def test_supported(selfwrap_grid):
    from igg.ops import stokes_pallas_supported

    import jax
    P = jax.ShapeDtypeStruct((16, 8, 8), np.float32)
    assert stokes_pallas_supported(selfwrap_grid, P)


def test_not_supported_wrong_overlap():
    from igg.ops import stokes_pallas_supported

    import jax
    igg.init_global_grid(16, 8, 8, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    P = jax.ShapeDtypeStruct((16, 8, 8), np.float32)
    assert not stokes_pallas_supported(igg.get_global_grid(), P)
    igg.finalize_global_grid()


def test_not_supported_open_boundary():
    from igg.ops import stokes_pallas_supported

    import jax
    igg.init_global_grid(16, 8, 8, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=0, periodz=1,
                         overlapx=3, overlapy=3, overlapz=3, quiet=True)
    P = jax.ShapeDtypeStruct((16, 8, 8), np.float32)
    assert not stokes_pallas_supported(igg.get_global_grid(), P)
    igg.finalize_global_grid()


def test_use_pallas_on_unsupported_grid_raises():
    igg.init_global_grid(16, 8, 8, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)  # ol 2
    params = stokes3d.Params()
    kw = stokes3d._pseudo_steps(params)
    fields = _fields()
    with pytest.raises(igg.GridError, match="fused Stokes"):
        stokes3d.local_iteration(*fields, **kw, use_pallas=True,
                                 pallas_interpret=True)
    igg.finalize_global_grid()


def test_matches_xla_one_iteration(selfwrap_grid):
    params = stokes3d.Params()
    kw = stokes3d._pseudo_steps(params)
    fields = _fields()
    ref = stokes3d.local_iteration(*fields, **kw)
    out = stokes3d.local_iteration(*fields, **kw, use_pallas=True,
                                   pallas_interpret=True)
    for name, a, b in zip(("P", "Vx", "Vy", "Vz"), ref, out):
        a, b = np.asarray(a), np.asarray(b)
        rel = np.max(np.abs(a - b)) / (np.max(np.abs(a)) + 1e-30)
        assert rel < 1e-6, (name, rel)


def test_make_iteration_pallas_through_sharded(selfwrap_grid):
    """The compiled entry (igg.sharded / shard_map + fori_loop): interpret
    kernels under shard_map need the check_vma workaround — this is the path
    the benchmark and driver dryrun use."""
    params = stokes3d.Params()
    it_x = stokes3d.make_iteration(params, n_inner=2, donate=False)
    it_p = stokes3d.make_iteration(params, n_inner=2, donate=False,
                                   use_pallas=True, pallas_interpret=True)
    fields = _fields()
    ref = it_x(*fields)
    out = it_p(*fields)
    for name, a, b in zip(("P", "Vx", "Vy", "Vz"), ref, out):
        a, b = np.asarray(a), np.asarray(b)
        rel = np.max(np.abs(a - b)) / (np.max(np.abs(a)) + 1e-30)
        assert rel < 1e-5, (name, rel)


def test_matches_xla_chained(selfwrap_grid):
    """Five chained iterations: halo invariants carried by the kernel feed
    the next iteration's windows."""
    params = stokes3d.Params()
    kw = stokes3d._pseudo_steps(params)
    fields = _fields()
    r = o = fields[:4]
    Rho = fields[4]
    for _ in range(5):
        r = stokes3d.local_iteration(*r, Rho, **kw)
        o = stokes3d.local_iteration(*o, Rho, **kw, use_pallas=True,
                                     pallas_interpret=True)
    for name, a, b in zip(("P", "Vx", "Vy", "Vz"), r, o):
        a, b = np.asarray(a), np.asarray(b)
        rel = np.max(np.abs(a - b)) / (np.max(np.abs(a)) + 1e-30)
        assert rel < 1e-5, (name, rel)
