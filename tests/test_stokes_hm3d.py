"""Stokes3D and HM3D model tests: multi-field staggered halo machinery under
real solvers; decomposition invariance is the key property."""

import numpy as np

import igg
from igg.models import hm3d, stokes3d


PER = dict(periodx=1, periody=1, periodz=1)


class TestStokes3D:
    def _run(self, nit, nx, **kw):
        igg.init_global_grid(nx, nx, nx, **PER, quiet=True, **kw)
        params = stokes3d.Params()
        P, Vx, Vy, Vz, Rho = stokes3d.init_fields(params, dtype=np.float64)
        it = stokes3d.make_iteration(params, donate=False,
                                     use_pallas=False)
        for _ in range(nit):
            P, Vx, Vy, Vz = it(P, Vx, Vy, Vz, Rho)
        out = tuple(igg.gather_interior(a) for a in (P, Vx, Vy, Vz))
        igg.finalize_global_grid()
        return out

    def test_decomposition_invariance(self):
        multi = self._run(10, 6)                      # dims (2,2,2): 8^3 global
        single = self._run(10, 10, dimx=1, dimy=1, dimz=1)
        for m, s, name in zip(multi, single, "P Vx Vy Vz".split()):
            assert m.shape == s.shape, name
            np.testing.assert_allclose(m, s, atol=1e-12, err_msg=name)

    def test_flow_develops_and_relaxes(self):
        igg.init_global_grid(8, 8, 8, **PER, quiet=True)
        params = stokes3d.Params()
        P, Vx, Vy, Vz, Rho = stokes3d.init_fields(params, dtype=np.float64)
        it = stokes3d.make_iteration(params, donate=False,
                                     use_pallas=False)

        def vz_update_norm(Vz_prev, Vz_next):
            return float(np.max(np.abs(igg.gather_interior(Vz_next)
                                       - igg.gather_interior(Vz_prev))))

        # early update magnitude (iteration 1 -> 2)
        P, Vx, Vy, Vz = it(P, Vx, Vy, Vz, Rho)
        Vz_a = Vz
        P, Vx, Vy, Vz = it(P, Vx, Vy, Vz, Rho)
        early = vz_update_norm(Vz_a, Vz)
        # late update magnitude (iteration 199 -> 200)
        for _ in range(197):
            P, Vx, Vy, Vz = it(P, Vx, Vy, Vz, Rho)
        Vz_b = Vz
        P, Vx, Vy, Vz = it(P, Vx, Vy, Vz, Rho)
        late = vz_update_norm(Vz_b, Vz)

        Vzg = igg.gather_interior(Vz)
        assert np.isfinite(Vzg).all()
        assert np.max(np.abs(Vzg)) > 1e-6        # buoyancy drives flow
        assert late < 0.5 * early                # pseudo-time relaxation


class TestHM3D:
    def _run(self, nt, nx, **kw):
        igg.init_global_grid(nx, nx, nx, **PER, quiet=True, **kw)
        params = hm3d.Params()
        Pe, phi = hm3d.init_fields(params, dtype=np.float64)
        step = hm3d.make_step(params, donate=False, use_pallas=False)
        for _ in range(nt):
            Pe, phi = step(Pe, phi)
        out = tuple(igg.gather_interior(a) for a in (Pe, phi))
        igg.finalize_global_grid()
        return out

    def test_decomposition_invariance(self):
        multi = self._run(10, 6)
        single = self._run(10, 10, dimx=1, dimy=1, dimz=1)
        for m, s, name in zip(multi, single, ("Pe", "phi")):
            assert m.shape == s.shape, name
            np.testing.assert_allclose(m, s, atol=1e-12, err_msg=name)

    def test_porosity_stays_physical(self):
        igg.init_global_grid(8, 8, 8, **PER, quiet=True)
        (Pe, phi), _ = hm3d.run(50, hm3d.Params(), dtype=np.float64)
        g = igg.gather_interior(phi)
        assert np.isfinite(g).all()
        assert (g > 0).all() and (g < 1).all()


class TestOverlap:
    """VERDICT round-1 item 7: comm/compute overlap for the BASELINE
    config-4/5 workloads.  On fully-periodic grids the hidden
    (slab-recompute) restructuring computes the same planes as the plain
    compute-then-exchange composition — equal to the last ulp (XLA fuses
    the thin-slab and full-domain computations differently, so FMA
    contraction may differ)."""

    def test_stokes_overlap_matches_plain(self):
        # Radius-2 update chain (velocities read fresh pressure): needs
        # overlap >= 3.
        results = {}
        for tag, ov in (("plain", False), ("hidden", True)):
            igg.init_global_grid(8, 8, 8, **PER, quiet=True,
                                 overlapx=3, overlapy=3, overlapz=3)
            params = stokes3d.Params()
            P, Vx, Vy, Vz, Rho = stokes3d.init_fields(params,
                                                      dtype=np.float64)
            it = stokes3d.make_iteration(params, donate=False, overlap=ov,
                                         use_pallas=False)
            for _ in range(6):
                P, Vx, Vy, Vz = it(P, Vx, Vy, Vz, Rho)
            results[tag] = [np.asarray(a) for a in (P, Vx, Vy, Vz)]
            igg.finalize_global_grid()
        for p, h, name in zip(results["plain"], results["hidden"],
                              "P Vx Vy Vz".split()):
            np.testing.assert_allclose(p, h, rtol=1e-12, atol=1e-17,
                                       err_msg=name)

    def test_stokes_overlap_requires_wide_halo(self):
        import pytest

        igg.init_global_grid(8, 8, 8, **PER, quiet=True)  # default ol=2
        params = stokes3d.Params()
        P, Vx, Vy, Vz, Rho = stokes3d.init_fields(params, dtype=np.float64)
        it = stokes3d.make_iteration(params, donate=False, overlap=True,
                                     use_pallas=False)
        with pytest.raises(igg.GridError, match="radius 2 exceeds"):
            it(P, Vx, Vy, Vz, Rho)

    def test_hm3d_overlap_matches_plain(self):
        results = {}
        for tag, ov in (("plain", False), ("hidden", True)):
            igg.init_global_grid(8, 8, 8, **PER, quiet=True)
            params = hm3d.Params()
            Pe, phi = hm3d.init_fields(params, dtype=np.float64)
            step = hm3d.make_step(params, donate=False, overlap=ov,
                                  use_pallas=False, n_inner=2)
            for _ in range(3):
                Pe, phi = step(Pe, phi)
            results[tag] = [np.asarray(a) for a in (Pe, phi)]
            igg.finalize_global_grid()
        for p, h, name in zip(results["plain"], results["hidden"],
                              ("Pe", "phi")):
            np.testing.assert_allclose(p, h, rtol=1e-12, atol=1e-17,
                                       err_msg=name)
