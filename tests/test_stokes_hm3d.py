"""Stokes3D and HM3D model tests: multi-field staggered halo machinery under
real solvers; decomposition invariance is the key property."""

import numpy as np

import igg
from igg.models import hm3d, stokes3d


PER = dict(periodx=1, periody=1, periodz=1)


class TestStokes3D:
    def _run(self, nit, nx, **kw):
        igg.init_global_grid(nx, nx, nx, **PER, quiet=True, **kw)
        params = stokes3d.Params()
        P, Vx, Vy, Vz, Rho = stokes3d.init_fields(params, dtype=np.float64)
        it = stokes3d.make_iteration(params, donate=False)
        for _ in range(nit):
            P, Vx, Vy, Vz = it(P, Vx, Vy, Vz, Rho)
        out = tuple(igg.gather_interior(a) for a in (P, Vx, Vy, Vz))
        igg.finalize_global_grid()
        return out

    def test_decomposition_invariance(self):
        multi = self._run(10, 6)                      # dims (2,2,2): 8^3 global
        single = self._run(10, 10, dimx=1, dimy=1, dimz=1)
        for m, s, name in zip(multi, single, "P Vx Vy Vz".split()):
            assert m.shape == s.shape, name
            np.testing.assert_allclose(m, s, atol=1e-12, err_msg=name)

    def test_flow_develops_and_relaxes(self):
        igg.init_global_grid(8, 8, 8, **PER, quiet=True)
        params = stokes3d.Params()
        P, Vx, Vy, Vz, Rho = stokes3d.init_fields(params, dtype=np.float64)
        it = stokes3d.make_iteration(params, donate=False)

        def vz_update_norm(Vz_prev, Vz_next):
            return float(np.max(np.abs(igg.gather_interior(Vz_next)
                                       - igg.gather_interior(Vz_prev))))

        # early update magnitude (iteration 1 -> 2)
        P, Vx, Vy, Vz = it(P, Vx, Vy, Vz, Rho)
        Vz_a = Vz
        P, Vx, Vy, Vz = it(P, Vx, Vy, Vz, Rho)
        early = vz_update_norm(Vz_a, Vz)
        # late update magnitude (iteration 199 -> 200)
        for _ in range(197):
            P, Vx, Vy, Vz = it(P, Vx, Vy, Vz, Rho)
        Vz_b = Vz
        P, Vx, Vy, Vz = it(P, Vx, Vy, Vz, Rho)
        late = vz_update_norm(Vz_b, Vz)

        Vzg = igg.gather_interior(Vz)
        assert np.isfinite(Vzg).all()
        assert np.max(np.abs(Vzg)) > 1e-6        # buoyancy drives flow
        assert late < 0.5 * early                # pseudo-time relaxation


class TestHM3D:
    def _run(self, nt, nx, **kw):
        igg.init_global_grid(nx, nx, nx, **PER, quiet=True, **kw)
        params = hm3d.Params()
        Pe, phi = hm3d.init_fields(params, dtype=np.float64)
        step = hm3d.make_step(params, donate=False)
        for _ in range(nt):
            Pe, phi = step(Pe, phi)
        out = tuple(igg.gather_interior(a) for a in (Pe, phi))
        igg.finalize_global_grid()
        return out

    def test_decomposition_invariance(self):
        multi = self._run(10, 6)
        single = self._run(10, 10, dimx=1, dimy=1, dimz=1)
        for m, s, name in zip(multi, single, ("Pe", "phi")):
            assert m.shape == s.shape, name
            np.testing.assert_allclose(m, s, atol=1e-12, err_msg=name)

    def test_porosity_stays_physical(self):
        igg.init_global_grid(8, 8, 8, **PER, quiet=True)
        (Pe, phi), _ = hm3d.run(50, hm3d.Params(), dtype=np.float64)
        g = igg.gather_interior(phi)
        assert np.isfinite(g).all()
        assert (g > 0).all() and (g < 1).all()
