"""Round-16 satellites: overlap serving composed with the ensemble tier
and the spec frontend (bitwise vs the sequential composition), the
wire-bytes-scored decomposition planners (`plane_wire_bytes`,
`dims_create` tie-break, `plan_dims` + its `dims_planned` telemetry),
and the `IGG_OVERLAP` knob's typed parsing / resolution order."""

import numpy as np
import pytest

import igg
from igg import GridError
from igg import telemetry as tel
from igg.fleet import plan_dims
from igg.topology import dims_create, plane_wire_bytes
from helpers import ensemble_states


def _stencil(A):
    """Radius-1 slice-based stencil (accepts any extent, writes its full
    shape) — the `hide_communication` contract shape from
    tests/test_overlap.py."""
    out = 0.1 * A
    for d in range(A.ndim):
        lo = [slice(None)] * A.ndim
        hi = [slice(None)] * A.ndim
        mid = [slice(None)] * A.ndim
        lo[d], hi[d], mid[d] = slice(0, -2), slice(2, None), slice(1, -1)
        out = out.at[tuple(mid)].add(0.15 * (A[tuple(lo)] + A[tuple(hi)]))
    return out


def _seq_member_step(st):
    return {"T": igg.update_halo_local(_stencil(st["T"]))}


def _ov_member_step(st):
    return {"T": igg.hide_communication(st["T"], _stencil)}


# ---------------------------------------------------------------------------
# hide_communication composed with run_ensemble (both packings)
# ---------------------------------------------------------------------------

def test_overlap_in_ensemble_grid_packing(eight_devices):
    """The overlapped member step serves bitwise-identical ensemble state
    under grid packing — hide_communication composes with the vmapped
    member axis inside one shard_map program."""
    igg.init_global_grid(6, 6, 6, periodx=1, periody=1, periodz=1,
                         quiet=True)                     # (2,2,2) mesh
    kw = dict(watch_every=0, install_sigterm=False, packing="grid")
    a = igg.run_ensemble(_seq_member_step, ensemble_states(3), 6, **kw)
    b = igg.run_ensemble(_ov_member_step, ensemble_states(3), 6, **kw)
    assert a.packing == b.packing == "grid"
    np.testing.assert_array_equal(np.asarray(a.state["T"]),
                                  np.asarray(b.state["T"]))


def test_overlap_in_ensemble_batch_packing(eight_devices):
    """Same contract under batch packing (dims=(1,1,1) grid, members on
    the batch axis): the exchange degenerates to local plane copies and
    the overlapped restructuring must still be value-identical."""
    igg.init_global_grid(6, 6, 6, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    kw = dict(watch_every=0, install_sigterm=False, packing="batch")
    a = igg.run_ensemble(_seq_member_step, ensemble_states(8), 6, **kw)
    b = igg.run_ensemble(_ov_member_step, ensemble_states(8), 6, **kw)
    assert a.packing == b.packing == "batch"
    np.testing.assert_array_equal(np.asarray(a.state["T"]),
                                  np.asarray(b.state["T"]))


# ---------------------------------------------------------------------------
# Spec-compiled steps: overlap=True bitwise vs the sequential composition
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("periods", [(1, 1, 1), (0, 0, 0), (1, 0, 1)])
def test_spec_compiled_overlap_matches_sequential(eight_devices, periods):
    """`igg.stencil.compile(..., overlap=True)` (admission via the
    analyzer's read-set radius) is bitwise the overlap=False compilation
    on periodic, open, and mixed 8-device meshes."""
    from igg import stencil

    igg.init_global_grid(6, 6, 6, periodx=periods[0], periody=periods[1],
                         periodz=periods[2], quiet=True)
    T = stencil.Field("T", stagger=(0, 0, 0))
    r = stencil.Param("r", default=0.1)
    lap = (T[-1, 0, 0] + T[1, 0, 0] + T[0, -1, 0] + T[0, 1, 0]
           + T[0, 0, -1] + T[0, 0, 1] - 6.0 * T[0, 0, 0])
    spec = stencil.StencilSpec(
        "relax3d", fields=[T], params=[r],
        updates=[stencil.Update(T, r * lap, pad=((1, 1),) * 3)])

    # Float64 (the suite default): bitwise across every boundary mix.
    # In float32, XLA's contraction choices may differ between the slab
    # and full-domain compilations of the same expression, leaving
    # 1-ulp differences on exchanged planes — the value contract there
    # is allclose (tests/test_overlap.py), not bitwise.
    rng = np.random.default_rng(7)
    A0 = igg.update_halo(igg.from_local_blocks(
        lambda c, ls: rng.standard_normal(ls), (6, 6, 6),
        dtype=np.float64))
    s_seq = stencil.compile(spec, donate=False, n_inner=4,
                            use_pallas=False, chunk=False, overlap=False)
    s_ov = stencil.compile(spec, donate=False, n_inner=4,
                           use_pallas=False, chunk=False, overlap=True)
    (a,) = s_seq(A0)
    (b,) = s_ov(A0)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# The wire-bytes model and the planner tie-breaks
# ---------------------------------------------------------------------------

def test_plane_wire_bytes_model():
    # (2,1,1) over (4,8,64) blocks: one split dim, 2 planes of
    # elems//local[0] = 512 cells, itemsize 8, nprocs 2.
    assert plane_wire_bytes((2, 1, 1), (4, 8, 64)) == 2 * 512 * 8 * 2
    # Unsplit mesh exchanges nothing over the wire.
    assert plane_wire_bytes((1, 1, 1), (4, 8, 64)) == 0
    # nfields scales linearly (the grouped-exchange accounting).
    assert (plane_wire_bytes((2, 2, 1), (8, 8, 8), nfields=3)
            == 3 * plane_wire_bytes((2, 2, 1), (8, 8, 8)))


def test_dims_create_tie_break_minimizes_wire_bytes():
    """Among permutations of the same balanced slot multiset, the one
    with the smallest predicted wire plane bytes for the job's local
    block wins; isotropic blocks keep the MPI_Dims_create order."""
    # Pancake block (4,4,256): the z planes are 16 cells vs 1024 for
    # x/y, so the split lands on z.
    assert dims_create(2, (0, 0, 0), local_shape=(4, 4, 256)) == (1, 1, 2)
    # Without the local shape: plain MPI_Dims_create non-increasing.
    assert dims_create(2, (0, 0, 0)) == (2, 1, 1)
    # Slots (2,2,1): the unsplit slot goes to the big-plane y axis
    # ((2,1,2) and (1,2,2) tie on bytes; reverse-lex keeps (2,1,2)).
    assert dims_create(4, (0, 0, 0), local_shape=(4, 4, 256)) == (2, 1, 2)
    # The chosen permutation really is a bytes-model argmin.
    import itertools
    chosen = dims_create(4, (0, 0, 0), local_shape=(4, 4, 256))
    best = min(plane_wire_bytes(p, (4, 4, 256))
               for p in set(itertools.permutations((2, 2, 1))))
    assert plane_wire_bytes(chosen, (4, 4, 256)) == best
    # Isotropic block: unchanged.
    assert dims_create(8, (0, 0, 0), local_shape=(16, 16, 16)) == (2, 2, 2)
    # Fixed entries are never touched: only the free slots permute
    # (z pinned to 2; the unsplit free slot lands on the big-plane y).
    assert (dims_create(4, (0, 0, 2), local_shape=(4, 256, 4))
            == (1, 2, 2))


def test_plan_dims_tie_break_and_telemetry():
    """Equal-balance factor triples are tie-broken by the wire-bytes
    score, balance stays PRIMARY, and the chosen mapping is logged as a
    `dims_planned` record carrying the per-link traffic."""
    # (8,8,64) periodic on 2 devices: (2,1,1)/(1,2,1)/(1,1,2) are all
    # balance-1; splitting z moves 2048 B/exchange vs 21120 for x/y.
    dims, local = plan_dims((8, 8, 64), 2)
    assert dims == (1, 1, 2) and local == (10, 10, 34)
    candidates = {(2, 1, 1): (6, 10, 66), (1, 2, 1): (10, 6, 66),
                  (1, 1, 2): (10, 10, 34)}
    assert (plane_wire_bytes(dims, local)
            == min(plane_wire_bytes(d, l) for d, l in candidates.items()))
    rec = [r for r in tel.flight_recorder()
           if r.kind == "dims_planned"][-1]
    assert rec.payload["dims"] == [1, 1, 2]
    assert rec.payload["candidates"] == 3
    assert rec.payload["hop_cost"] == "uniform"       # CPU: no coords
    (link,) = rec.payload["per_link"]
    assert link["dim"] == "z" and link["devices"] == 2
    assert link["wire_bytes_per_exchange"] == plane_wire_bytes(dims, local)
    assert link["mean_link_hops"] == 1.0

    # Balance stays primary: (4,2,1) would move fewer wire bytes than
    # (2,2,2) on an (8,8,8) interior (fewer split dims), but the
    # MPI_Dims_create balance contract wins.
    dims, local = plan_dims((8, 8, 8), 8)
    assert dims == (2, 2, 2)
    assert (plane_wire_bytes((4, 2, 1), (4, 6, 10))
            < plane_wire_bytes((2, 2, 2), (6, 6, 6)))


# ---------------------------------------------------------------------------
# IGG_OVERLAP: typed parsing + the resolve_overlap order
# ---------------------------------------------------------------------------

def test_igg_overlap_flag_parsing(monkeypatch):
    from igg import _env

    for v in ("1", "true", "YES", "on"):
        monkeypatch.setenv("IGG_OVERLAP", v)
        assert _env.flag("IGG_OVERLAP") is True, v
    for v in ("0", "false", "no", "OFF", ""):
        monkeypatch.setenv("IGG_OVERLAP", v)
        assert _env.flag("IGG_OVERLAP") is False, v
    monkeypatch.setenv("IGG_OVERLAP", "maybe")
    with pytest.raises(GridError, match="IGG_OVERLAP"):
        _env.flag("IGG_OVERLAP")
    monkeypatch.delenv("IGG_OVERLAP")
    assert _env.flag("IGG_OVERLAP") is False
    assert _env.flag("IGG_OVERLAP", default=True) is True
    assert "IGG_OVERLAP" in _env._KNOWN     # registered: no typo warning


def test_resolve_overlap_env_overrides_tuned(monkeypatch, eight_devices):
    from igg.overlap import resolve_overlap

    igg.init_global_grid(6, 6, 6, periodx=1, periody=1, periodz=1,
                         quiet=True)
    monkeypatch.delenv("IGG_OVERLAP", raising=False)
    # No winner, no env: sequential stays the default.
    assert resolve_overlap("auto", family="diffusion3d") is False
    # The cached winner's overlap axis serves.
    assert resolve_overlap("auto", family="diffusion3d",
                           tuned={"overlap": True}) is True
    # A set IGG_OVERLAP beats the winner in BOTH directions.
    monkeypatch.setenv("IGG_OVERLAP", "0")
    assert resolve_overlap("auto", family="diffusion3d",
                           tuned={"overlap": True}) is False
    monkeypatch.setenv("IGG_OVERLAP", "1")
    assert resolve_overlap("auto", family="diffusion3d",
                           tuned={"overlap": False}) is True
    # Admission still gates a forced True: radius beyond ol-1 degrades
    # to the sequential composition (logged, never raising).
    assert resolve_overlap("auto", family="diffusion3d",
                           radius=5) is False
    assert "radius 5" in igg.degrade.admission_log()["diffusion3d.overlap"]
    # Explicit caller pins bypass resolution entirely.
    assert resolve_overlap(True, family="diffusion3d") is True
    assert resolve_overlap(False, family="diffusion3d") is False
    with pytest.raises(GridError, match="overlap"):
        resolve_overlap("sometimes", family="diffusion3d")
