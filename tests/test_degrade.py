"""The verified tier-degradation ladder (`igg.degrade`, round 10): kernel
quarantine with compile-failure capture, numeric verify-on-first-use
against the pure-XLA composition truth, the chaos injectors that prove
both guards on the 8-device CPU mesh, and the `run_resilient` recovery
rung that demotes a deterministically-blowing-up tier with zero
user-supplied policy code.
"""

import warnings

import numpy as np
import pytest

import igg
from igg import degrade
from igg.models import diffusion3d, stokes3d


PERIODIC = dict(periodx=1, periody=1, periodz=1)


def _init_diffusion():
    igg.init_global_grid(8, 8, 128, dimx=2, dimy=2, dimz=2, **PERIODIC,
                         quiet=True)


def _diffusion_state(params=None):
    params = params or diffusion3d.Params()
    return diffusion3d.init_fields(params)


def _xla_reference(T, Cp, n=1, params=None):
    params = params or diffusion3d.Params()
    step = diffusion3d.make_step(params, use_pallas=False, donate=False)
    for _ in range(n):
        T = step(T, Cp)
    return np.asarray(T)


@pytest.fixture(autouse=True)
def _clean_ladder():
    degrade.reset()
    yield
    degrade.reset()


class TestAdmission:
    def test_truthy_falsy_and_reason(self):
        assert degrade.Admission.yes()
        no = degrade.Admission.no("because")
        assert not no
        assert no.reason == "because"
        assert "because" in repr(no)

    def test_ops_gates_return_structured_reasons(self):
        from igg.ops import pallas_supported, stokes_pallas_supported

        _init_diffusion()
        grid = igg.get_global_grid()
        T, _ = _diffusion_state()
        adm = pallas_supported(grid, T)
        assert adm and isinstance(adm, degrade.Admission)
        # Wrong overlap for the Stokes kernel: falsy with a named gate.
        ref = stokes_pallas_supported(grid, T)
        assert not ref
        assert "overlaps" in ref.reason

    def test_trapezoid_gate_reasons(self):
        from igg.ops import stokes_trapezoid_supported
        from igg.ops.diffusion_trapezoid import trapezoid_supported

        _init_diffusion()
        grid = igg.get_global_grid()
        no_chunk = trapezoid_supported(grid, (8, 8, 128), 8, 2, np.float32)
        assert not no_chunk and "chunk" in no_chunk.reason
        bad = stokes_trapezoid_supported(grid, (8, 8, 128), 4, 8,
                                         np.float32, interpret=True)
        assert not bad and "overlaps" in bad.reason

    def test_admission_log_records_refusals(self):
        _init_diffusion()
        T, Cp = _diffusion_state()
        # CPU mesh, interpret off: the mosaic rung refuses with a reason.
        step = diffusion3d.make_step(donate=False)
        step(T, Cp)
        log = degrade.admission_log()
        assert "not TPU" in log.get("diffusion3d.mosaic", "")
        assert degrade.active().get("diffusion3d") == "diffusion3d.xla"


class TestCompileFailureCapture:
    def test_quarantine_and_bitexact_fallback(self):
        """A chaos-forced Mosaic compile failure ends in a COMPLETED
        dispatch bit-exact to the pure-XLA composition — no crash, no
        wrong answer — with the tier quarantined and the error captured."""
        _init_diffusion()
        T, Cp = _diffusion_state()
        ref = _xla_reference(T + 0, Cp)
        with igg.chaos.kernel_compile_fail("diffusion3d.mosaic",
                                           "chaos: no Mosaic today"):
            step = diffusion3d.make_step(pallas_interpret=True, donate=False)
            with warnings.catch_warnings(record=True) as w:
                warnings.simplefilter("always")
                out = step(T + 0, Cp)
        np.testing.assert_array_equal(np.asarray(out), ref)
        q = degrade.status()["diffusion3d.mosaic"]
        assert q.reason == "compile_failed"
        assert "chaos: no Mosaic today" in q.error
        assert degrade.active()["diffusion3d"] == "diffusion3d.xla"
        assert any("quarantined" in str(x.message) for x in w)
        events = degrade.events()
        assert events and events[-1]["kind"] == "tier_degraded"

    def test_one_time_warning(self):
        _init_diffusion()
        T, Cp = _diffusion_state()
        with igg.chaos.kernel_compile_fail("diffusion3d.mosaic"):
            step = diffusion3d.make_step(pallas_interpret=True, donate=False)
            with warnings.catch_warnings(record=True) as w:
                warnings.simplefilter("always")
                step(T + 0, Cp)
                step(T + 0, Cp)
        msgs = [x for x in w if "quarantined" in str(x.message)]
        assert len(msgs) == 1

    def test_required_tier_raises(self):
        """use_pallas=True keeps its contract: a required tier whose first
        compile fails raises GridError instead of silently degrading."""
        _init_diffusion()
        T, Cp = _diffusion_state()
        with igg.chaos.kernel_compile_fail("diffusion3d.mosaic"):
            step = diffusion3d.make_step(use_pallas=True,
                                         pallas_interpret=True, donate=False)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                with pytest.raises(igg.GridError, match="required"):
                    step(T + 0, Cp)
        # ... and stays refused on the next dispatch, naming the capture.
        step2 = diffusion3d.make_step(use_pallas=True,
                                      pallas_interpret=True, donate=False)
        with pytest.raises(igg.GridError, match="quarantined"):
            step2(T + 0, Cp)

    def test_reset_readmits(self):
        _init_diffusion()
        T, Cp = _diffusion_state()
        with igg.chaos.kernel_compile_fail("diffusion3d.mosaic"):
            step = diffusion3d.make_step(pallas_interpret=True, donate=False)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                step(T + 0, Cp)
        assert degrade.is_quarantined("diffusion3d.mosaic")
        degrade.reset("diffusion3d.mosaic")
        assert not degrade.is_quarantined("diffusion3d.mosaic")
        # Healthy again: a fresh factory serves the fast tier.
        step2 = diffusion3d.make_step(pallas_interpret=True, donate=False)
        step2(T + 0, Cp)
        assert degrade.active()["diffusion3d"] == "diffusion3d.mosaic"


class TestVerifyFirstUse:
    def test_corrupt_kernel_never_serves_wrong_answer(self):
        """A chaos-corrupted kernel output under verify="first_use" ends in
        a COMPLETED dispatch bit-exact to the XLA composition: the
        mismatch quarantines the tier BEFORE it serves traffic."""
        _init_diffusion()
        T, Cp = _diffusion_state()
        ref = _xla_reference(T + 0, Cp)
        with igg.chaos.kernel_corrupt("diffusion3d.mosaic", magnitude=1e3):
            step = diffusion3d.make_step(pallas_interpret=True, donate=False,
                                         verify="first_use")
            with warnings.catch_warnings(record=True) as w:
                warnings.simplefilter("always")
                out = step(T + 0, Cp)
        np.testing.assert_array_equal(np.asarray(out), ref)
        q = degrade.status()["diffusion3d.mosaic"]
        assert q.reason == "verify_mismatch"
        assert "beyond tolerance" in q.error
        assert any("quarantined" in str(x.message) for x in w)

    def test_healthy_tier_passes_verify_once(self):
        _init_diffusion()
        T, Cp = _diffusion_state()
        ref = _xla_reference(T + 0, Cp, n=2)
        step = diffusion3d.make_step(pallas_interpret=True, donate=False,
                                     verify="first_use")
        out = step(step(T + 0, Cp), Cp)
        assert degrade.status() == {}
        assert degrade.active()["diffusion3d"] == "diffusion3d.mosaic"
        # Interpret-mode Pallas matches the XLA composition bit-exactly on
        # this stencil; the guard's tolerance gate never engaged.
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6)

    def test_env_knob_enables_verify(self, monkeypatch):
        monkeypatch.setenv("IGG_VERIFY_KERNELS", "1")
        _init_diffusion()
        T, Cp = _diffusion_state()
        with igg.chaos.kernel_corrupt("diffusion3d.mosaic", magnitude=1e3):
            step = diffusion3d.make_step(pallas_interpret=True, donate=False)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                step(T + 0, Cp)
        assert (degrade.status()["diffusion3d.mosaic"].reason
                == "verify_mismatch")

    def test_verify_mode_validated(self):
        _init_diffusion()
        with pytest.raises(igg.GridError, match="verify"):
            diffusion3d.make_step(verify="always")


class TestStokesLadder:
    def test_multi_rung_fall(self):
        """Both fast Stokes rungs chaos-quarantined: trapezoid falls to the
        per-iteration mosaic rung, mosaic falls to the XLA truth, and the
        result is bit-exact to the pure composition."""
        igg.init_global_grid(16, 16, 128, dimx=2, dimy=2, dimz=2, **PERIODIC,
                             overlapx=3, overlapy=3, overlapz=3, quiet=True)
        params = stokes3d.Params(lx=4.0, ly=4.0, lz=4.0)
        P, Vx, Vy, Vz, Rho = stokes3d.init_fields(params, dtype=np.float32)
        ref_it = stokes3d.make_iteration(params, donate=False,
                                         use_pallas=False, n_inner=5)
        ref = [np.asarray(a) for a in ref_it(P, Vx, Vy, Vz, Rho)]
        with igg.chaos.armed(
                igg.chaos.kernel_compile_fail("stokes3d.trapezoid"),
                igg.chaos.kernel_compile_fail("stokes3d.mosaic")):
            it = stokes3d.make_iteration(params, donate=False, n_inner=5,
                                         pallas_interpret=True)
            with warnings.catch_warnings(record=True) as w:
                warnings.simplefilter("always")
                out = it(P, Vx, Vy, Vz, Rho)
        for a, b in zip(ref, out):
            np.testing.assert_array_equal(a, np.asarray(b))
        assert set(degrade.status()) == {"stokes3d.trapezoid",
                                         "stokes3d.mosaic"}
        assert degrade.active()["stokes3d"] == "stokes3d.xla"
        assert len([x for x in w if "quarantined" in str(x.message)]) == 2

    def test_trapezoid_rung_admitted_and_healthy(self):
        igg.init_global_grid(16, 16, 128, dimx=2, dimy=2, dimz=2, **PERIODIC,
                             overlapx=3, overlapy=3, overlapz=3, quiet=True)
        params = stokes3d.Params(lx=4.0, ly=4.0, lz=4.0)
        P, Vx, Vy, Vz, Rho = stokes3d.init_fields(params, dtype=np.float32)
        it = stokes3d.make_iteration(params, donate=False, n_inner=5,
                                     pallas_interpret=True)
        it(P, Vx, Vy, Vz, Rho)
        assert degrade.active()["stokes3d"] == "stokes3d.trapezoid"
        assert degrade.status() == {}


class TestDemoteActive:
    def test_demotes_fast_tier_not_truth(self):
        _init_diffusion()
        T, Cp = _diffusion_state()
        step = diffusion3d.make_step(pallas_interpret=True, donate=False)
        step(T + 0, Cp)
        assert degrade.active()["diffusion3d"] == "diffusion3d.mosaic"
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            demoted = degrade.demote_active(error_text="test recurrence")
        assert demoted == ["diffusion3d.mosaic"]
        assert (degrade.status()["diffusion3d.mosaic"].reason
                == "nan_recurrence")
        # Nothing left to demote: the truth rung serves now.
        step2 = diffusion3d.make_step(pallas_interpret=True, donate=False)
        step2(T + 0, Cp)
        assert degrade.demote_active() == []

    def test_since_scopes_demotion_to_the_run(self):
        """A family warmed BEFORE the failing run must not be demoted by
        that run's recovery (`demote_active(since=stamp)`)."""
        _init_diffusion()
        T, Cp = _diffusion_state()
        step = diffusion3d.make_step(pallas_interpret=True, donate=False)
        step(T + 0, Cp)               # warmed before the "run" starts
        mark = degrade.dispatch_stamp()
        assert degrade.demote_active(since=mark) == []
        assert not degrade.is_quarantined("diffusion3d.mosaic")
        step(T + 0, Cp)               # dispatched inside the "run"
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            assert degrade.demote_active(since=mark) == \
                ["diffusion3d.mosaic"]

    def test_served_memory_survives_factory_recreation(self):
        """Once a tier has served, a RECREATED factory's first-dispatch
        failure is a real error (propagates), not a compile failure to
        quarantine — the served memory is process-wide like quarantine."""
        _init_diffusion()
        T, Cp = _diffusion_state()
        step = diffusion3d.make_step(pallas_interpret=True, donate=False)
        step(T + 0, Cp)               # the tier has served
        with igg.chaos.kernel_compile_fail("diffusion3d.mosaic"):
            fresh = diffusion3d.make_step(pallas_interpret=True,
                                          donate=False)
            with pytest.raises(degrade.InjectedCompileError):
                fresh(T + 0, Cp)
        assert not degrade.is_quarantined("diffusion3d.mosaic")


class TestResilientTierDemotion:
    def test_recovery_with_zero_policy_code(self, tmp_path):
        """A chaos-corrupted kernel (NaN every dispatch — rollback cannot
        heal it) recovers via tier demotion within the default retry
        budget, bit-exact to the pure-XLA run, with NO recovery_policy."""
        _init_diffusion()
        params = diffusion3d.Params()
        T, Cp = _diffusion_state(params)
        ref_step = diffusion3d.make_step(params, use_pallas=False,
                                         donate=False)
        ref = {"T": T + 0}
        for _ in range(20):
            ref["T"] = ref_step(ref["T"], Cp)
        step = diffusion3d.make_step(params, pallas_interpret=True,
                                     donate=False)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with igg.chaos.kernel_corrupt("diffusion3d.mosaic"):
                res = igg.run_resilient(
                    lambda s: {"T": step(s["T"], Cp)}, {"T": T + 0}, 20,
                    watch_every=5, checkpoint_dir=tmp_path,
                    checkpoint_every=5, async_checkpoint=False)
        assert res.steps_done == 20
        assert res.retries <= 3   # within the default budget
        kinds = [e.kind for e in res.events]
        assert "tier_degraded" in kinds
        deg = next(e for e in res.events if e.kind == "tier_degraded")
        assert deg.detail["tier"] == "diffusion3d.mosaic"
        assert degrade.is_quarantined("diffusion3d.mosaic")
        np.testing.assert_array_equal(np.asarray(res.state["T"]),
                                      np.asarray(ref["T"]))

    def test_resilience_error_carries_events(self, tmp_path):
        """Exhaustion hands the postmortem the full event history."""
        _init_diffusion()
        T, Cp = _diffusion_state()
        step = diffusion3d.make_step(use_pallas=False, donate=False)
        plan = igg.chaos.ChaosPlan(nan_at=[(4, "T"), (9, "T"), (14, "T")])
        with pytest.raises(igg.ResilienceError) as ei:
            igg.run_resilient(
                lambda s: {"T": step(s["T"], Cp)}, {"T": T + 0}, 20,
                watch_every=5, checkpoint_dir=tmp_path, checkpoint_every=5,
                async_checkpoint=False, max_retries=1, chaos=plan)
        evs = ei.value.events
        assert [e.kind for e in evs].count("nan_detected") >= 2
        assert any(e.kind == "rollback" for e in evs)


class TestHaloWriterTier:
    def test_quarantine_disables_writer_election(self):
        from igg import halo

        igg.init_global_grid(8, 16, 256, **PERIODIC, quiet=True)
        A = igg.zeros((8, 16, 256), dtype=np.float32)
        halo._FORCE_WRITER_INTERPRET = True
        try:
            grid = igg.get_global_grid()
            dims = halo.moving_dims(halo.active_dims(A.shape, grid), grid)
            _, use_writer = halo._writer_dims(A, dims, grid)
            assert use_writer
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                degrade.quarantine(degrade.HALO_WRITER_TIER, 0,
                                   "compile_failed",
                                   error_text="test injection")
            _, use_writer = halo._writer_dims(A, dims, grid)
            assert not use_writer
            # The forced-writer contract names the quarantine.
            with pytest.raises(igg.GridError, match="quarantined"):
                igg.update_halo(A, assembly="pallas")
        finally:
            halo._FORCE_WRITER_INTERPRET = False

    def test_compile_fail_capture_falls_to_xla(self):
        """Chaos-injected writer compile failure: update_halo completes on
        the XLA plans, the tier is quarantined, the answer is the oracle's."""
        from helpers import roundtrip

        from igg import halo

        igg.init_global_grid(8, 16, 256, **PERIODIC, quiet=True)
        halo._FORCE_WRITER_INTERPRET = True
        try:
            with igg.chaos.kernel_compile_fail(degrade.HALO_WRITER_TIER):
                with warnings.catch_warnings(record=True) as w:
                    warnings.simplefilter("always")
                    out, exp = roundtrip((8, 16, 256), dtype=np.float32)
            np.testing.assert_array_equal(out, exp.astype(np.float32))
            q = degrade.status()[degrade.HALO_WRITER_TIER]
            assert q.reason == "compile_failed"
            assert any("quarantined" in str(x.message) for x in w)
        finally:
            halo._FORCE_WRITER_INTERPRET = False


class TestChaosContextManagers:
    def test_armed_disarms_on_exception(self):
        kc = igg.chaos.kernel_corrupt("some.tier", 1.0)
        with pytest.raises(RuntimeError, match="boom"):
            with igg.chaos.armed(kc):
                assert degrade._CHAOS_TIER_TAP is not None
                raise RuntimeError("boom")
        assert degrade._CHAOS_TIER_TAP is None

    def test_armed_resets_chaos_plan(self):
        plan = igg.chaos.ChaosPlan(nan_at=[(3, "T")])
        plan._fired.add(("nan", 3, "T", None))
        with igg.chaos.armed(plan) as p:
            assert p is plan
            assert not plan._fired   # re-armed on entry
            plan._fired.add(("nan", 3, "T", None))
        assert not plan._fired       # consumed state cannot leak

    def test_stacked_injectors_unwind(self):
        a = igg.chaos.kernel_compile_fail("t.a")
        b = igg.chaos.kernel_corrupt("t.b", 2.0)
        with igg.chaos.armed(a, b) as (ia, ib):
            tap = degrade._CHAOS_TIER_TAP
            assert tap["compile_fail"]["t.a"] is None
            assert tap["corrupt"]["t.b"] == 2.0
        assert degrade._CHAOS_TIER_TAP is None

    def test_imperative_wrappers_still_work(self):
        kc = igg.chaos.kernel_compile_fail("t.c").arm()
        assert "t.c" in degrade._CHAOS_TIER_TAP["compile_fail"]
        kc.disarm()
        assert degrade._CHAOS_TIER_TAP is None


class TestEnvRegistry:
    def test_unknown_igg_var_warns_once(self, monkeypatch):
        from igg import _env

        monkeypatch.setenv("IGG_VERIFY_KERNEL", "1")   # typo'd knob
        monkeypatch.setattr(_env, "_warned_unknown", False)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            _env.flag("IGG_VERIFY_KERNELS")
            _env.flag("IGG_VERIFY_KERNELS")
        msgs = [x for x in w if "IGG_VERIFY_KERNEL" in str(x.message)]
        assert len(msgs) == 1
        assert "no effect" in str(msgs[0].message)

    def test_typed_accessors_raise_grid_error(self, monkeypatch):
        from igg import _env

        monkeypatch.setattr(_env, "_warned_unknown", True)
        monkeypatch.setenv("IGG_CKPT_COMMIT_TIMEOUT", "ten")
        with pytest.raises(igg.GridError, match="IGG_CKPT_COMMIT_TIMEOUT"):
            _env.number("IGG_CKPT_COMMIT_TIMEOUT", 600)
        monkeypatch.setenv("IGG_VERIFY_KERNELS", "maybe")
        with pytest.raises(igg.GridError, match="boolean"):
            _env.flag("IGG_VERIFY_KERNELS")

    def test_flag_spellings(self, monkeypatch):
        from igg import _env

        monkeypatch.setattr(_env, "_warned_unknown", True)
        for val, want in [("1", True), ("true", True), ("ON", True),
                          ("0", False), ("off", False), ("", False)]:
            monkeypatch.setenv("IGG_VERIFY_KERNELS", val)
            assert _env.flag("IGG_VERIFY_KERNELS") is want

    def test_register_extends_registry(self, monkeypatch):
        from igg import _env

        monkeypatch.setattr(_env, "_KNOWN", dict(_env._KNOWN))
        _env.register("IGG_TEST_KNOB", "test-only")
        monkeypatch.setenv("IGG_TEST_KNOB", "7")
        assert _env.integer("IGG_TEST_KNOB", 0) == 7
        with pytest.raises(igg.GridError, match="IGG_"):
            _env.register("NOT_IGG", "nope")


class TestLifecycle:
    def test_finalize_clears_ladder_state(self):
        _init_diffusion()
        T, Cp = _diffusion_state()
        with igg.chaos.kernel_compile_fail("diffusion3d.mosaic"):
            step = diffusion3d.make_step(pallas_interpret=True, donate=False)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                step(T + 0, Cp)
        assert degrade.status()
        igg.finalize_global_grid()
        assert degrade.status() == {}
        assert degrade.events() == []
        assert degrade.active() == {}

    def test_ladder_requires_truth_rung(self):
        with pytest.raises(igg.GridError, match="truth"):
            degrade.Ladder("fam", [degrade.Tier(name="fam.fast", rung=0,
                                                build=lambda: None)])
