"""igg.autotune — the ledger-driven (tier, K, bx, vmem) search, the
on-disk tuning cache, the factory `tune=` application, and the
heal-loop staleness interplay (perf.invalidate evicting cached
winners), on the 8-device interpret mesh."""

import json
import pathlib

import numpy as np
import pytest

import igg
from igg import autotune, perf
from igg import telemetry as tel


@pytest.fixture(autouse=True)
def _clean(tmp_path, monkeypatch):
    """Isolated ledger + tuning cache per test (both are process-global
    by design); the cache file lives under tmp_path."""
    monkeypatch.setenv("IGG_TUNE_CACHE", str(tmp_path / "tune.json"))
    perf.reset()
    autotune.reset()
    tel.reset_metrics()
    tel._ring().clear()
    yield
    perf.reset()
    autotune.reset()
    tel.reset_metrics()


def _diffusion_grid():
    igg.init_global_grid(16, 16, 128, dimx=8, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    from igg.models import diffusion3d as d3

    return d3, d3.Params(lx=8.0, ly=8.0, lz=60.0)


# ---------------------------------------------------------------------------
# The knob contract
# ---------------------------------------------------------------------------

def test_resolve_contract(monkeypatch):
    assert autotune.resolve(False) is False
    assert autotune.resolve(True) is True
    assert autotune.resolve("auto") == "auto"
    assert autotune.resolve(None) == "auto"        # env unset
    monkeypatch.setenv("IGG_TUNE", "0")
    assert autotune.resolve(None) is False
    monkeypatch.setenv("IGG_TUNE", "1")
    assert autotune.resolve(None) is True
    with pytest.raises(igg.GridError):
        autotune.resolve("sometimes")
    monkeypatch.setenv("IGG_TUNE", "banana")
    with pytest.raises(igg.GridError):
        autotune.resolve(None)


def test_applied_off_and_no_grid():
    # tune=False and no-grid are both clean no-ops.
    assert autotune.applied("diffusion3d", False) is None
    assert autotune.applied("diffusion3d", "auto") is None


# ---------------------------------------------------------------------------
# The search: empty-ledger seed -> winner <= the hand-picked config
# ---------------------------------------------------------------------------

def test_search_converges_and_beats_hand_picked():
    """Seeded with an EMPTY ledger, the search over (tier, K/bx)
    candidates for f32 diffusion on the smoke mesh must converge to a
    winner whose measured step time is <= the hand-picked bx=8
    candidate's, enrich the ledger with autotune-sourced samples, and
    persist the winner."""
    d3, params = _diffusion_grid()
    assert perf.best("diffusion3d") is None      # empty-ledger seed
    w = autotune.search("diffusion3d", n_inner=9, params=params,
                        interpret=True, nt=1)
    assert w is not None and w["tier"].startswith("diffusion3d.")
    assert autotune.search_dispatches() > 0
    # Winner <= the hand-picked K=8 config, from the search's own
    # samples on the bus.
    samples = [r.payload for r in tel.flight_recorder()
               if r.kind == "autotune_sample"]
    hand = [s for s in samples if "bx=8" in s["candidate"]]
    assert hand, samples
    assert w["ms"] <= min(s["ms_per_step"] for s in hand) * (1 + 1e-9)
    # The ledger is now the enriched prior.
    entries = perf.query("diffusion3d")
    assert entries and all("autotune" in e["sources"] for e in entries)
    # Winner persisted to the on-disk cache, versioned format.
    doc = json.loads(pathlib.Path(autotune.cache_path()).read_text())
    assert doc["format"] == autotune.TUNE_FORMAT
    assert any(e["family"] == "diffusion3d"
               for e in doc["entries"].values())


def test_prior_orders_candidates_first():
    """A ledger prior puts its tier's candidates first in the walk (the
    cutoff threshold is then set by the likely winner)."""
    d3, params = _diffusion_grid()
    ctx = autotune._context("diffusion3d")
    perf.record("diffusion3d", "diffusion3d.xla", 0.5, source="calibrate",
                local_shape=ctx["local_shape"], dtype="float32",
                dims=ctx["dims"], backend=ctx["backend"],
                device_kind=ctx["device_kind"])
    w = autotune.search("diffusion3d", n_inner=9, params=params,
                        interpret=True, nt=1)
    samples = [r.payload for r in tel.flight_recorder()
               if r.kind == "autotune_sample"]
    assert samples[0]["candidate"].startswith("[diffusion3d.xla")
    assert w is not None


# ---------------------------------------------------------------------------
# Cache round trip: the second process performs zero search dispatches
# ---------------------------------------------------------------------------

def test_cache_round_trip_zero_search():
    d3, params = _diffusion_grid()
    w = autotune.search("diffusion3d", n_inner=9, params=params,
                        interpret=True, nt=1)
    # "Second process": fresh in-memory state, same cache file.
    autotune.reset()
    assert autotune.search_dispatches() == 0
    w2 = autotune.applied("diffusion3d", "auto")
    assert w2 is not None and w2["tier"] == w["tier"]
    assert w2.get("bx") == w.get("bx")
    # tune=True with a cache HIT must not search either.
    w3 = autotune.applied("diffusion3d", True, n_inner=9, params=params,
                         interpret=True)
    assert w3 is not None and autotune.search_dispatches() == 0
    # The factory consumes the winner without dispatching a search.
    step = d3.make_multi_step(9, params, donate=False, tune="auto",
                              pallas_interpret=True)
    assert autotune.search_dispatches() == 0
    T, Cp = d3.init_fields(params, dtype=np.float32)
    step(T, Cp)   # serves normally with the tuned config applied


def test_explicit_args_beat_cached_winner():
    """A caller-pinned bx must never be overridden by the cache."""
    d3, params = _diffusion_grid()
    autotune.record_winner("diffusion3d",
                           {"tier": "diffusion3d.mosaic", "K": 4, "bx": 4,
                            "vmem_mb": None, "ms": 0.1})
    captured = {}
    import igg.ops as ops

    real = ops.fused_diffusion_steps

    def spy(T, Cp, **kw):
        captured["bx"] = kw.get("bx")
        return real(T, Cp, **kw)

    step = d3.make_multi_step(9, params, donate=False, tune="auto",
                              pallas_interpret=True, bx=8,
                              use_pallas=True)
    import igg.models.diffusion3d  # noqa: F401  (factory built above)
    try:
        ops.fused_diffusion_steps = spy
        T, Cp = d3.init_fields(params, dtype=np.float32)
        step(T, Cp)
    finally:
        ops.fused_diffusion_steps = real
    assert captured.get("bx") == 8


# ---------------------------------------------------------------------------
# Staleness: drift -> perf.invalidate -> tuning-cache eviction
# ---------------------------------------------------------------------------

def test_perf_invalidate_evicts_tuning_cache():
    """The heal-loop interplay: a ``cost_model_drift``-driven
    :func:`igg.perf.invalidate` must evict the family's cached winner —
    memory AND disk — so a drifted machine re-tunes instead of serving
    a stale winner."""
    autotune.record_winner("myfam", {"tier": "myfam.mosaic", "K": 8,
                                     "bx": 8, "vmem_mb": None, "ms": 1.0},
                           local_shape=(32, 32, 32))
    assert autotune.get("myfam", local_shape=(32, 32, 32)) is not None
    # A stale prediction + measured samples fire cost_model_drift...
    perf.predict("myfam", 0.010)                      # 10 ms predicted
    perf.record("myfam", "myfam.mosaic", 2.0, local_shape=(32, 32, 32),
                dtype="float32")
    drifts = [r for r in tel.flight_recorder()
              if r.kind == "cost_model_drift"]
    assert drifts and drifts[0].payload["family"] == "myfam"
    # ...whose heal action is recalibrate -> perf.invalidate -> eviction
    # (myfam is not a model family, so recalibrate re-anchors to the
    # freshest sample instead of dispatching a calibration).
    igg.heal.recalibrate("myfam")
    assert autotune.get("myfam", local_shape=(32, 32, 32)) is None
    evs = [r for r in tel.flight_recorder() if r.kind == "tune_invalidated"]
    assert evs and evs[0].payload["family"] == "myfam"
    # Durable: the on-disk cache no longer carries the entry either.
    path = autotune.cache_path()
    if path.exists():
        doc = json.loads(path.read_text())
        assert not any(e.get("family") == "myfam"
                       for e in doc["entries"].values())
    # And a recalibrated event closed the loop.
    assert any(r.kind == "recalibrated" for r in tel.flight_recorder())


def test_invalidate_tier_scoped():
    autotune.record_winner("famA", {"tier": "famA.mosaic", "K": None,
                                    "bx": 8, "vmem_mb": None, "ms": 1.0},
                           local_shape=(8, 8, 8))
    assert autotune.invalidate("famA", tier="famA.trapezoid") == 0
    assert autotune.get("famA", local_shape=(8, 8, 8)) is not None
    assert autotune.invalidate("famA", tier="famA.mosaic") == 1
    assert autotune.get("famA", local_shape=(8, 8, 8)) is None


# ---------------------------------------------------------------------------
# Persistence: merge-on-write, newest wins, corrupt-file tolerance
# ---------------------------------------------------------------------------

def test_save_merges_and_newest_wins(tmp_path):
    p = tmp_path / "tune.json"
    autotune.record_winner("f1", {"tier": "f1.xla", "K": None, "bx": None,
                                  "vmem_mb": None, "ms": 2.0},
                           local_shape=(8, 8, 8))
    autotune.save(p)
    # A "concurrent" process writes a different family...
    autotune.reset()
    autotune.record_winner("f2", {"tier": "f2.xla", "K": None, "bx": None,
                                  "vmem_mb": None, "ms": 3.0},
                           local_shape=(8, 8, 8))
    autotune.save(p)
    doc = json.loads(p.read_text())
    fams = {e["family"] for e in doc["entries"].values()}
    assert fams == {"f1", "f2"}       # merge-on-write lost nothing
    # ...and a NEWER winner for f1 replaces the old one.
    autotune.reset()
    autotune.record_winner("f1", {"tier": "f1.mosaic", "K": 8, "bx": 8,
                                  "vmem_mb": None, "ms": 1.0},
                           local_shape=(8, 8, 8))
    autotune.save(p)
    doc = json.loads(p.read_text())
    f1 = [e for e in doc["entries"].values() if e["family"] == "f1"]
    assert len(f1) == 1 and f1[0]["tier"] == "f1.mosaic"


def test_corrupt_cache_never_fatal(tmp_path, monkeypatch):
    p = tmp_path / "bad.json"
    p.write_text("{not json")
    monkeypatch.setenv("IGG_TUNE_CACHE", str(p))
    autotune.reset()
    assert autotune.get("anything", local_shape=(4, 4, 4)) is None
    with pytest.raises(igg.GridError):
        autotune.load(p)


def test_cached_K_falls_back_to_fit_on_smaller_n_inner():
    """The cache key has no n_inner axis: a tuned K=8 winner applied to
    a factory whose n_inner only fits K=4 must FALL BACK to the
    auto-fitted depth and still serve the chunk tier (a caller-pinned K
    keeps hard-refusing — `_dispatch.resolve_chunk_K`)."""
    _diffusion_grid()   # same mesh works for hm3d's 16x16x128 blocks
    from igg.models import hm3d

    autotune.record_winner("hm3d", {"tier": "hm3d.trapezoid", "K": 8,
                                    "bx": None, "vmem_mb": None,
                                    "ms": 1.0})
    p = hm3d.Params(lx=4.0, ly=4.0, lz=4.0)
    Pe, phi = hm3d.init_fields(p, dtype=np.float32)
    # n_inner=5: only K=4 fits (warm-up + one chunk).  The cached K=8
    # must not disable the tier.
    step = hm3d.make_step(p, donate=False, n_inner=5, use_pallas=True,
                          pallas_interpret=True, trapezoid="auto",
                          tune="auto")
    step(Pe, phi)
    assert igg.degrade.active().get("hm3d") == "hm3d.trapezoid"
    # A CALLER-pinned inapplicable K still hard-refuses.
    pinned = hm3d.make_step(p, donate=False, n_inner=5, use_pallas=True,
                            pallas_interpret=True, trapezoid=True, K=8,
                            tune=False)
    with pytest.raises(igg.GridError, match="chunk tier"):
        pinned(Pe, phi)


def test_applied_normalizes_vmem_cap():
    """The process-global VMEM cap follows the factory being built: a
    capped winner installs it, a miss or tune=False clears it."""
    from igg.ops import _vmem

    _diffusion_grid()
    try:
        autotune.record_winner("diffusion3d",
                               {"tier": "diffusion3d.mosaic", "K": 8,
                                "bx": 8, "vmem_mb": 64, "ms": 1.0})
        w = autotune.applied("diffusion3d", "auto")
        assert w is not None and _vmem.vmem_cap() == 64 * 1024 * 1024
        # A MISS for another family clears the leaked cap.
        assert autotune.applied("stokes3d", "auto") is None
        assert _vmem.vmem_cap() == _vmem.VMEM_CAP
        # Reinstall, then an explicitly-untuned factory clears it too.
        autotune.applied("diffusion3d", "auto")
        assert _vmem.vmem_cap() == 64 * 1024 * 1024
        assert autotune.applied("diffusion3d", False) is None
        assert _vmem.vmem_cap() == _vmem.VMEM_CAP
    finally:
        _vmem.set_cap_override(None)
