"""Round-13 satellites: Prometheus label-value escaping, the name-level
metric type-collision guard, StepStats edge behavior (first-fetch
anchor, near-zero-dt suppression), snapshot() under concurrent
observe(), benchmarks/run_all.py failed-stdout salvage + parents-created
results dirs, and the telemetry merge CLI's --trace Chrome-trace
emission."""

import importlib.util
import json
import pathlib
import sys
import threading

import pytest

import igg
from igg import telemetry as tel

ROOT = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _clean_telemetry():
    tel.reset_metrics()
    tel._ring().clear()
    yield
    for s in list(tel._SESSIONS):
        s.detach()
    tel.reset_metrics()


# ---------------------------------------------------------------------------
# (i) Prometheus exposition: label-value escaping per the text-format spec
# ---------------------------------------------------------------------------

def test_prometheus_label_values_are_escaped():
    # A path-bearing / free-text label value with every character the
    # spec requires escaping: backslash, double-quote, newline.
    tel.counter("igg_esc_total", run='C:\\runs\\r1 "smoke"\nline2').inc(2)
    text = tel.prometheus_text()
    line = next(l for l in text.splitlines()
                if l.startswith("igg_esc_total{"))
    assert line == ('igg_esc_total{run="C:\\\\runs\\\\r1 \\"smoke\\"'
                    '\\nline2"} 2.0')
    # The exposition stays line-parseable: no raw newline or unescaped
    # quote inside the label braces of ANY line.
    for l in text.splitlines():
        if not l or l.startswith("#"):
            continue
        name, value = l.rsplit(" ", 1)
        float(value)
        inner = name[name.index("{") + 1:name.rindex("}")] \
            if "{" in name else ""
        assert "\n" not in inner
        assert inner.count('"') % 2 == 0


def test_prometheus_escape_helper():
    assert tel._prom_label_value('a"b') == 'a\\"b'
    assert tel._prom_label_value("a\\b") == "a\\\\b"
    assert tel._prom_label_value("a\nb") == "a\\nb"
    assert tel._prom_label_value("plain") == "plain"


# ---------------------------------------------------------------------------
# (ii) metric type collision is caught at the NAME level
# ---------------------------------------------------------------------------

def test_metric_type_collision_across_label_sets():
    """PR 7 only caught a type collision at the exact (name, labels) key
    — a counter `x{a=..}` next to a gauge `x{b=..}` slipped through and
    rendered an exposition whose single `# TYPE x` line lies about one
    of them.  One name, one type, across EVERY label set."""
    tel.counter("igg_col_total", tier="a").inc()
    with pytest.raises(igg.GridError, match="one name, one type"):
        tel.gauge("igg_col_total", member="2")
    with pytest.raises(igg.GridError, match="one name, one type"):
        tel.histogram("igg_col_total")
    # Same type, different labels: still fine.
    tel.counter("igg_col_total", tier="b").inc()
    # reset clears the name-level memory with the registry.
    tel.reset_metrics()
    tel.gauge("igg_col_total").set(1.0)


# ---------------------------------------------------------------------------
# (iii) StepStats edges: first-fetch anchor, tiny-dt suppression
# ---------------------------------------------------------------------------

def _stats_records():
    return [r for r in tel.flight_recorder() if r.kind == "step_stats"]


def test_stepstats_first_fetch_only_anchors(monkeypatch):
    import time as _time

    clock = {"t": 1000.0}
    monkeypatch.setattr(_time, "monotonic", lambda: clock["t"])
    s = tel.StepStats("t_first")
    s.fetched(10, 12)
    # One fetch = an anchor, not a window: no rate can exist yet.
    assert _stats_records() == []
    assert tel.snapshot()['igg_steps_per_s{run="t_first"}']["value"] == 0.0
    # ...but the fetch lag IS already known.
    assert tel.snapshot()['igg_watchdog_fetch_lag_steps'
                          '{run="t_first"}']["value"] == 2.0
    clock["t"] += 2.0
    s.fetched(30, 30)
    recs = _stats_records()
    assert len(recs) == 1
    assert recs[0].payload["steps_per_s"] == pytest.approx(10.0)
    assert recs[0].payload["window_steps"] == 20


def test_stepstats_suppresses_drain_bursts(monkeypatch):
    """A drain materializes several queued probes back-to-back: the
    near-zero deltas (dt < _MIN_DT) must be skipped, not extrapolated
    into nonsense rates; non-advancing probe steps are skipped too."""
    import time as _time

    clock = {"t": 500.0}
    monkeypatch.setattr(_time, "monotonic", lambda: clock["t"])
    s = tel.StepStats("t_burst")
    s.fetched(10, 10)
    clock["t"] += 1.0
    s.fetched(20, 20)
    assert len(_stats_records()) == 1
    # Burst: three more probes land within a fraction of _MIN_DT.
    for step in (30, 40, 50):
        clock["t"] += tel.StepStats._MIN_DT / 10
        s.fetched(step, 50)
    assert len(_stats_records()) == 1      # all suppressed
    # dsteps <= 0 (a re-probed step) is suppressed even with real dt.
    clock["t"] += 5.0
    s.fetched(50, 55)
    assert len(_stats_records()) == 1
    # The anchor kept moving: the next healthy window is measured from
    # the LAST fetch, not from before the burst.
    clock["t"] += 1.0
    s.fetched(60, 60)
    recs = _stats_records()
    assert len(recs) == 2
    assert recs[-1].payload["steps_per_s"] == pytest.approx(10.0)


# ---------------------------------------------------------------------------
# (iv) snapshot() under concurrent observe()
# ---------------------------------------------------------------------------

def test_snapshot_under_concurrent_observe():
    h = tel.histogram("igg_conc_lat")
    c = tel.counter("igg_conc_total")
    n_threads, per = 4, 2000
    start = threading.Barrier(n_threads + 1)
    snaps = []

    def worker():
        start.wait()
        for i in range(per):
            h.observe(float(i % 7))
            c.inc()

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    start.wait()
    # Snapshot (and render) WHILE observers hammer the registry: must
    # never raise or return a torn histogram (count behind a concurrent
    # read is fine; a crash or a key error is not).
    for _ in range(50):
        snap = tel.snapshot()
        tel.prometheus_text()
        if "igg_conc_lat" in snap:
            assert snap["igg_conc_lat"]["count"] <= n_threads * per
    for t in threads:
        t.join()
    snap = tel.snapshot()
    assert snap["igg_conc_lat"]["count"] == n_threads * per
    assert snap["igg_conc_lat"]["min"] == 0.0
    assert snap["igg_conc_lat"]["max"] == 6.0
    assert snap["igg_conc_total"]["value"] == float(n_threads * per)


# ---------------------------------------------------------------------------
# (v) benchmarks/run_all.py: failed-stdout salvage, parents created
# ---------------------------------------------------------------------------

def _run_all_mod():
    spec = importlib.util.spec_from_file_location(
        "igg_test_run_all", ROOT / "benchmarks" / "run_all.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_run_all_salvages_failed_partial_stdout(tmp_path, capsys):
    ra = _run_all_mod()
    script = tmp_path / "boom.py"
    script.write_text(
        "import sys\n"
        "print('{\"metric\": \"partial\", \"value\": 1}')\n"
        "print('dying now', file=sys.stderr)\n"
        "sys.exit(3)\n")
    results = tmp_path / "deep" / "nested" / "results"   # parents absent
    with pytest.raises(SystemExit):
        ra.run(str(script), [], tag="boom", results=results)
    saved = results / "boom.failed.jsonl"
    assert saved.exists()
    assert json.loads(saved.read_text())["metric"] == "partial"
    assert not (results / "boom.jsonl").exists()   # never a clean artifact
    err = capsys.readouterr().err
    assert "partial stdout" in err and "boom failed (exit 3)" in err


def test_run_all_creates_result_parents_on_success(tmp_path):
    ra = _run_all_mod()
    script = tmp_path / "ok.py"
    script.write_text("print('{\"metric\": \"fine\", \"value\": 2}')\n")
    results = tmp_path / "also" / "missing" / "results"
    ra.run(str(script), [], tag="ok", results=results)
    assert json.loads((results / "ok.jsonl").read_text())["value"] == 2
    assert not (results / "ok.failed.jsonl").exists()


# ---------------------------------------------------------------------------
# (vi) telemetry merge CLI: --trace emits one merged Chrome trace
# ---------------------------------------------------------------------------

def _span_line(process, wall, name):
    return json.dumps({
        "t": wall, "wall": wall, "process": process, "kind": "span",
        "step": None,
        "payload": {"name": name, "dur_s": 0.5, "wall0": wall,
                    "tid": 7, "extra": "x"}})


def test_merge_cli_trace_flag_merges_rank_spans(tmp_path):
    (tmp_path / "events_r0.jsonl").write_text(
        _span_line(0, 10.0, "ckpt") + "\n"
        + json.dumps({"t": 11.0, "wall": 11.0, "process": 0,
                      "kind": "rollback", "step": 5, "payload": {}})
        + "\n")
    (tmp_path / "events_r1.jsonl").write_text(
        _span_line(1, 10.5, "rollback_load") + "\n")
    trace = tmp_path / "merged_trace.json"
    rc = tel._main(["merge", "--trace", str(trace),
                    str(tmp_path / "merged.jsonl"), str(tmp_path)])
    assert rc == 0
    # The merged JSONL holds all three records, wall-ordered.
    merged = [json.loads(l) for l in
              (tmp_path / "merged.jsonl").read_text().splitlines()]
    assert [r["kind"] for r in merged] == ["span", "span", "rollback"]
    # The trace holds BOTH ranks' spans in one Perfetto-valid file.
    doc = json.loads(trace.read_text())
    evs = doc["traceEvents"]
    assert len(evs) == 2
    assert {e["pid"] for e in evs} == {0, 1}
    assert {e["name"] for e in evs} == {"ckpt", "rollback_load"}
    assert all(e["ph"] == "X" and isinstance(e["ts"], float)
               and e["dur"] == pytest.approx(0.5e6) for e in evs)
    assert evs[0]["args"]["extra"] == "x"
    # Flag plumbing: --trace without a value is a usage error.
    assert tel._main(["merge", str(tmp_path / "m2.jsonl"),
                      str(tmp_path), "--trace"]) == 2
