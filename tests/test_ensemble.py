"""The ensemble tier (igg/ensemble.py) on the 8-device CPU mesh: M
independent members in ONE compiled program, with every per-member
isolation path PROVEN through the member-targeted chaos injectors —
per-member attribution of the fused probe (single, multiple-simultaneous,
and member-0 edge), isolated rollback (healthy members bit-identical to an
uninterrupted run), retry-budget quarantine (the batch completes),
preemption + elastic resume onto a different decomposition, and both
packings (grid-sharded members and the batch-axis NamedSharding)."""

import numpy as np
import pytest

import igg
from helpers import ensemble_member_step, ensemble_states


def _grid(**kw):
    args = dict(periodx=1, periody=1, periodz=1, quiet=True)
    args.update(kw)
    igg.init_global_grid(6, 6, 6, **args)          # (2,2,2) mesh


def _clean(step_fn, states, n, **kw):
    """Uninterrupted ensemble run — the bit-exactness oracle."""
    return igg.run_ensemble(step_fn, states, n, watch_every=0,
                            install_sigterm=False, **kw)


# ---------------------------------------------------------------------------
# Per-member attribution: the M-vector probe fingers exactly the injected
# member(s)
# ---------------------------------------------------------------------------

def test_probe_attributes_single_member(tmp_path):
    _grid()
    step = ensemble_member_step()
    plan = igg.chaos.ChaosPlan(nan_at=[(7, 2, "T")])
    res = igg.run_ensemble(step, ensemble_states(4), 20, watch_every=5,
                           checkpoint_dir=tmp_path, checkpoint_every=5,
                           chaos=plan)
    div = [e for e in res.events if e.kind == "member_diverged"]
    assert len(div) == 1
    assert div[0].detail["members"] == [2]          # exactly the injected one
    assert 7 < div[0].step <= 12                    # within one watch window
    assert div[0].detail["counts"]["T"].keys() == {2}
    assert res.quarantined == []


def test_probe_attributes_multiple_simultaneous_members(tmp_path):
    """Two members blowing up inside the SAME watch window are both
    fingered by one probe — and only them."""
    _grid()
    step = ensemble_member_step()
    plan = igg.chaos.ChaosPlan(nan_at=[(6, 1, "T"), (7, 3, "T")])
    res = igg.run_ensemble(step, ensemble_states(5), 20, watch_every=5,
                           checkpoint_dir=tmp_path, checkpoint_every=5,
                           chaos=plan)
    div = [e for e in res.events if e.kind == "member_diverged"]
    assert div and div[0].detail["members"] == [1, 3]
    rb = next(e for e in res.events if e.kind == "member_rollback")
    assert rb.detail["members"] == [1, 3]
    assert res.quarantined == []


def test_probe_attributes_member_zero_edge(tmp_path):
    """Member 0 — the edge lane of the stacked axis — is attributed like
    any other (an off-by-one in the lane indexing would misattribute or
    miss it)."""
    _grid()
    step = ensemble_member_step()
    plan = igg.chaos.ChaosPlan(nan_at=[(7, 0, "T")])
    res = igg.run_ensemble(step, ensemble_states(3), 20, watch_every=5,
                           checkpoint_dir=tmp_path, checkpoint_every=5,
                           chaos=plan)
    div = [e for e in res.events if e.kind == "member_diverged"]
    assert div and div[0].detail["members"] == [0]
    assert res.quarantined == []


# ---------------------------------------------------------------------------
# Isolation: rollback restores ONLY the diverged member
# ---------------------------------------------------------------------------

def test_isolated_recovery_bit_exact(tmp_path):
    """One member NaNs; the run recovers with only that member rolled
    back, and EVERY member — the recovered one included — finishes
    bit-identical to an uninterrupted run."""
    _grid()
    step = ensemble_member_step()
    states = ensemble_states(4)
    ref = np.asarray(_clean(step, states, 20).state["T"])

    plan = igg.chaos.ChaosPlan(nan_at=[(7, 2, "T")])
    res = igg.run_ensemble(step, states, 20, watch_every=5,
                           checkpoint_dir=tmp_path, checkpoint_every=5,
                           chaos=plan)
    rb = next(e for e in res.events if e.kind == "member_rollback")
    assert rb.detail["members"] == [2]
    assert res.steps_done == 20 and res.retries == {2: 1}
    np.testing.assert_array_equal(np.asarray(res.state["T"]), ref)


def test_rollback_skips_lane_poisoned_generation(tmp_path):
    """A generation written between the blowup and its detection holds the
    poisoned LANE; the per-lane finite gate must skip it for that member
    and land on the older healthy one — while the same generation would
    still serve a different member."""
    _grid()
    step = ensemble_member_step()
    states = ensemble_states(3)
    ref = np.asarray(_clean(step, states, 20).state["T"])
    # checkpoint_every=2 < watch_every=10: gens 8/10 are written after the
    # step-7 injection but before the step-10 probe is fetched.
    plan = igg.chaos.ChaosPlan(nan_at=[(7, 1, "T")])
    res = igg.run_ensemble(step, states, 20, watch_every=10,
                           checkpoint_dir=tmp_path, checkpoint_every=2,
                           ring=10, chaos=plan)
    rb = next(e for e in res.events if e.kind == "member_rollback")
    assert rb.step <= 6                 # not the lane-poisoned 8/10 gens
    np.testing.assert_array_equal(np.asarray(res.state["T"]), ref)


def test_member_scalar_parameters_sweep(tmp_path):
    """Per-member scalar fields (the parameter-sweep shape) flow through
    the vmapped step — members genuinely differ — and survive checkpoint
    round-trips bit-exactly via the sidecar."""
    _grid()
    step = ensemble_member_step()
    scales = [1.0, 0.5, 2.0, 1.25]
    states = ensemble_states(4, rate_scales=scales)
    res = igg.run_ensemble(step, states, 10, watch_every=5,
                           checkpoint_dir=tmp_path, checkpoint_every=5)
    got = np.asarray(res.state["T"])
    assert not np.array_equal(got[0], got[1])      # the sweep is real
    np.testing.assert_array_equal(np.asarray(res.state["rate_scale"]),
                                  np.asarray(scales))
    # The sidecar carries the parameter lanes bit-exactly.
    out = igg.run_ensemble(step, [{k: np.zeros_like(np.asarray(v))
                                   for k, v in st.items()}
                                  for st in states], 10,
                           watch_every=5, checkpoint_dir=tmp_path,
                           checkpoint_every=5, resume=True)
    np.testing.assert_array_equal(np.asarray(out.state["rate_scale"]),
                                  np.asarray(scales))
    np.testing.assert_array_equal(np.asarray(out.state["T"]), got)


# ---------------------------------------------------------------------------
# Quarantine: retry-budget exhaustion isolates, the batch completes
# ---------------------------------------------------------------------------

def test_retry_exhaustion_quarantines_member_batch_completes(tmp_path):
    """A persistently-faulting member exhausts its per-member budget and
    is QUARANTINED (masked out of step and verdict) instead of raising
    ResilienceError for the batch; healthy members finish bit-identical
    to an uninterrupted run."""
    _grid()
    step = ensemble_member_step()
    states = ensemble_states(4)
    ref = np.asarray(_clean(step, states, 20).state["T"])

    plan = igg.chaos.ChaosPlan(
        nan_at=[(s, 1, "T") for s in (6, 7, 8, 9, 11, 12, 13, 14, 16, 17)])
    res = igg.run_ensemble(step, states, 20, watch_every=5,
                           checkpoint_dir=tmp_path, checkpoint_every=5,
                           member_retries=2, chaos=plan)
    assert res.quarantined == [1]
    q = next(e for e in res.events if e.kind == "member_quarantined")
    assert q.detail["member"] == 1 and q.detail["reason"] == "retry_budget"
    assert res.steps_done == 20
    for m in (0, 2, 3):
        np.testing.assert_array_equal(np.asarray(res.state["T"])[m],
                                      ref[m])


def test_no_rollback_target_quarantines_not_raises():
    """Detection with no ring configured quarantines the member (reason
    no_rollback_target) — the batch completes; only an ALL-quarantined
    ensemble raises."""
    _grid()
    step = ensemble_member_step()
    plan = igg.chaos.ChaosPlan(nan_at=[(3, 1, "T")])
    res = igg.run_ensemble(step, ensemble_states(3), 10, watch_every=5,
                           chaos=plan)
    assert res.quarantined == [1]
    q = next(e for e in res.events if e.kind == "member_quarantined")
    assert q.detail["reason"] == "no_rollback_target"
    assert res.steps_done == 10


def test_all_members_quarantined_raises():
    _grid()
    step = ensemble_member_step()
    plan = igg.chaos.ChaosPlan(nan_at=[(3, 0, "T"), (3, 1, "T")])
    with pytest.raises(igg.ResilienceError, match="every member"):
        igg.run_ensemble(step, ensemble_states(2), 10, watch_every=5,
                         chaos=plan)


def test_quarantine_persists_through_resume(tmp_path):
    """The sidecar carries quarantine state: a resumed ensemble masks the
    NaN lane instead of re-detecting (and re-paying retries for) it."""
    _grid()
    step = ensemble_member_step()
    plan = igg.chaos.ChaosPlan(
        nan_at=[(s, 0, "T") for s in (2, 3, 6, 7, 8, 9, 11, 12)],
        preempt_at=15)
    res = igg.run_ensemble(step, ensemble_states(3), 25, watch_every=5,
                           checkpoint_dir=tmp_path, checkpoint_every=5,
                           member_retries=1, chaos=plan)
    assert res.preempted and res.quarantined == [0]

    res2 = igg.run_ensemble(step, ensemble_states(3), 25, watch_every=5,
                            checkpoint_dir=tmp_path, checkpoint_every=5,
                            member_retries=1, resume=True)
    assert res2.events[0].kind == "resume"
    assert res2.events[0].detail["quarantined"] == [0]
    assert res2.quarantined == [0] and res2.steps_done == 25
    assert not any(e.kind == "member_diverged" for e in res2.events)


# ---------------------------------------------------------------------------
# Preemption + elastic resume
# ---------------------------------------------------------------------------

def test_preempt_and_elastic_resume_different_topology(tmp_path):
    """A preempted ensemble on the (2,2,2) mesh resumes on a (1,2,4)
    decomposition — every member's interior finishes bit-identical to an
    uninterrupted (2,2,2) run (the acceptance criterion)."""
    _grid()
    step = ensemble_member_step()
    states = ensemble_states(3)
    clean = _clean(step, states, 20)
    ref = np.stack([np.asarray(igg.gather_interior(clean.state["T"][m]))
                    for m in range(3)])

    plan = igg.chaos.ChaosPlan(preempt_at=10)
    res = igg.run_ensemble(step, states, 20, watch_every=5,
                           checkpoint_dir=tmp_path, checkpoint_every=5,
                           chaos=plan)
    assert res.preempted and res.steps_done == 10
    igg.finalize_global_grid()

    # Same periodic global domain (2*(6-2) = 8 per dim) on (1,2,4).
    igg.init_global_grid(10, 6, 4, dimx=1, dimy=2, dimz=4,
                         periodx=1, periody=1, periodz=1, quiet=True)
    dummy = ensemble_states(3, lshape=(10, 6, 4), seed=99)
    res2 = igg.run_ensemble(step, dummy, 20, watch_every=5,
                            checkpoint_dir=tmp_path, checkpoint_every=5,
                            resume=True)
    assert res2.events[0].kind == "resume" and res2.events[0].step == 10
    assert res2.steps_done == 20
    got = np.stack([np.asarray(igg.gather_interior(res2.state["T"][m]))
                    for m in range(3)])
    np.testing.assert_array_equal(got, ref)


def test_rollback_after_elastic_resume_uses_old_geometry_gens(tmp_path):
    """A divergence right after an elastic resume — before any
    post-resume cadence write — must roll back into the OLD
    decomposition's generations (elastic lane restore), not quarantine
    the member because those generations 'mismatch' the live grid."""
    _grid()
    step = ensemble_member_step()
    states = ensemble_states(3)
    clean = _clean(step, states, 20)
    ref = np.stack([np.asarray(igg.gather_interior(clean.state["T"][m]))
                    for m in range(3)])
    plan = igg.chaos.ChaosPlan(preempt_at=10)
    res = igg.run_ensemble(step, states, 20, watch_every=5,
                           checkpoint_dir=tmp_path, checkpoint_every=20,
                           chaos=plan)
    assert res.preempted and res.steps_done == 10
    igg.finalize_global_grid()

    # Resume on (1,2,4); checkpoint_every=20 means NO new generation
    # exists when member 1 NaNs at step 12 — the only rollback targets
    # are the (2,2,2)-geometry generations.
    igg.init_global_grid(10, 6, 4, dimx=1, dimy=2, dimz=4,
                         periodx=1, periody=1, periodz=1, quiet=True)
    dummy = ensemble_states(3, lshape=(10, 6, 4), seed=99)
    plan2 = igg.chaos.ChaosPlan(nan_at=[(12, 1, "T")])
    res2 = igg.run_ensemble(step, dummy, 20, watch_every=5,
                            checkpoint_dir=tmp_path, checkpoint_every=20,
                            resume=True, chaos=plan2)
    assert res2.quarantined == []                  # rolled back, not lost
    rb = next(e for e in res2.events if e.kind == "member_rollback")
    assert rb.detail["members"] == [1]
    got = np.stack([np.asarray(igg.gather_interior(res2.state["T"][m]))
                    for m in range(3)])
    np.testing.assert_array_equal(got, ref)


# ---------------------------------------------------------------------------
# Batch packing (the batch-axis NamedSharding)
# ---------------------------------------------------------------------------

def test_batch_packing_auto_and_isolation(tmp_path):
    """On a dims=(1,1,1) grid with 8 devices available, auto packing
    shards the MEMBER axis (one compiled program, M/8 members per
    device); attribution and isolated recovery hold there too."""
    import jax

    igg.init_global_grid(8, 8, 8, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True,
                         devices=jax.devices()[:1])
    step = ensemble_member_step()
    states = ensemble_states(16, lshape=(8, 8, 8))
    clean = _clean(step, states, 10)
    assert clean.packing == "batch"

    plan = igg.chaos.ChaosPlan(nan_at=[(3, 9, "T")])
    res = igg.run_ensemble(step, states, 10, watch_every=5,
                           checkpoint_dir=tmp_path, checkpoint_every=5,
                           chaos=plan)
    assert res.packing == "batch"
    div = [e for e in res.events if e.kind == "member_diverged"]
    assert div and div[0].detail["members"] == [9]
    np.testing.assert_array_equal(np.asarray(res.state["T"]),
                                  np.asarray(clean.state["T"]))


def test_batch_to_grid_elastic_resume(tmp_path):
    """A batch-packed ensemble's generation resumes GRID-packed on the
    (2,2,2) mesh — the lane layout is packing-agnostic."""
    import jax

    igg.init_global_grid(8, 8, 8, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True,
                         devices=jax.devices()[:1])
    step = ensemble_member_step()
    states = ensemble_states(8, lshape=(8, 8, 8))
    clean = _clean(step, states, 10)
    ref = np.stack([np.asarray(igg.gather_interior(clean.state["T"][m]))
                    for m in range(8)])
    plan = igg.chaos.ChaosPlan(preempt_at=5)
    res = igg.run_ensemble(step, states, 10, watch_every=5,
                           checkpoint_dir=tmp_path, checkpoint_every=5,
                           chaos=plan)
    assert res.preempted and res.packing == "batch"
    igg.finalize_global_grid()

    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    dummy = ensemble_states(8, lshape=(5, 5, 5), seed=42)
    res2 = igg.run_ensemble(step, dummy, 10, watch_every=5,
                            checkpoint_dir=tmp_path, checkpoint_every=5,
                            resume=True)
    assert res2.packing == "grid" and res2.steps_done == 10
    got = np.stack([np.asarray(igg.gather_interior(res2.state["T"][m]))
                    for m in range(8)])
    np.testing.assert_array_equal(got, ref)


# ---------------------------------------------------------------------------
# Contract validation
# ---------------------------------------------------------------------------

def test_resume_matching_nothing_owns_a_fresh_ring(tmp_path):
    """resume=True over generations no candidate can serve (wrong member
    count) starts fresh AND clears them: left in place, the stale
    high-step generations would win every newest-`ring` prune and the
    fresh run would have no rollback target."""
    _grid()
    step = ensemble_member_step()
    # A previous 2-member run leaves gens at high steps.
    igg.run_ensemble(step, ensemble_states(2), 200, watch_every=100,
                     checkpoint_dir=tmp_path, checkpoint_every=100)
    # A 3-member resume can use none of them.
    states = ensemble_states(3)
    ref = np.asarray(_clean(step, states, 20).state["T"])
    plan = igg.chaos.ChaosPlan(nan_at=[(7, 1, "T")])
    res = igg.run_ensemble(step, states, 20, watch_every=5,
                           checkpoint_dir=tmp_path, checkpoint_every=5,
                           ring=3, resume=True, chaos=plan)
    assert not any(e.kind == "resume" for e in res.events)
    # The divergence still had a rollback target (the fresh ring
    # survived pruning) — no quarantine, bit-exact recovery.
    assert res.quarantined == [] and res.steps_done == 20
    np.testing.assert_array_equal(np.asarray(res.state["T"]), ref)
    from igg.checkpoint import list_generations
    steps = [s for s, _ in list_generations(tmp_path, "ens")]
    assert max(steps) == 20 and 100 not in steps and 200 not in steps


def test_argument_validation(tmp_path):
    _grid()
    step = ensemble_member_step()
    states = ensemble_states(2)
    with pytest.raises(igg.GridError, match="members"):
        igg.run_ensemble(step, {"T": np.zeros((2, 12, 12, 12))}, 10)
    with pytest.raises(igg.GridError, match="checkpoint_dir"):
        igg.run_ensemble(step, states, 10, checkpoint_every=5)
    with pytest.raises(igg.GridError, match="steps_per_call"):
        igg.run_ensemble(step, states, 10, steps_per_call=3)
    with pytest.raises(igg.GridError, match="packing"):
        igg.run_ensemble(step, states, 10, packing="bogus")
    with pytest.raises(igg.GridError, match="batch"):
        igg.run_ensemble(step, states, 10, packing="batch")   # (2,2,2) grid
    # member-targeted chaos entries validate eagerly
    with pytest.raises(igg.GridError, match="member-targeted"):
        igg.chaos.ChaosPlan(nan_at=[(3, 1)])


def test_preempt_during_catchup_completes_replay_first(tmp_path):
    """A preemption that lands while a rollback cohort is mid-replay (and
    a chaos plan is still armed) must let the cohort reach the front and
    then preempt — the round-11 review hang: the chaos block's preempt
    skip starving the replay forever."""
    _grid()
    step = ensemble_member_step()
    states = ensemble_states(3)
    # NaN at 2 detected by the step-4 probe; preempt fires at 3 — i.e.
    # BEFORE the rollback, so the whole catch-up replay runs with the
    # preemption flag already set.
    plan = igg.chaos.ChaosPlan(nan_at=[(2, 0, "T")], preempt_at=3)
    res = igg.run_ensemble(step, states, 8, watch_every=4,
                           checkpoint_dir=tmp_path, checkpoint_every=4,
                           chaos=plan)
    assert res.preempted and res.quarantined == []
    assert any(e.kind == "member_rollback" for e in res.events)
    # The recovered lane is healthy in the final (preemption) generation.
    res2 = igg.run_ensemble(step, states, 8, watch_every=4,
                            checkpoint_dir=tmp_path, checkpoint_every=4,
                            resume=True)
    assert res2.steps_done == 8 and res2.quarantined == []
    ref = np.asarray(_clean(step, states, 8).state["T"])
    np.testing.assert_array_equal(np.asarray(res2.state["T"]), ref)


def test_tail_rollback_rewrites_stale_final_generation(tmp_path):
    """A divergence caught at the front AFTER the cadence generation at
    that step was written: the tail rollback replays the lane, and the
    final generation must be REWRITTEN (not just re-sealed) so
    `result.checkpoint` holds the returned, healthy state."""
    import jax.numpy as jnp

    _grid()
    step = ensemble_member_step()
    states = ensemble_states(3)
    ref = np.asarray(_clean(step, states, 10).state["T"])
    # watch_every == n_steps: the only probe fires at the front, after
    # the poisoned cadence generation at step 10 is already on disk.
    plan = igg.chaos.ChaosPlan(nan_at=[(7, 1, "T")])
    res = igg.run_ensemble(step, states, 10, watch_every=10,
                           checkpoint_dir=tmp_path, checkpoint_every=5,
                           chaos=plan)
    assert res.steps_done == 10 and res.quarantined == []
    np.testing.assert_array_equal(np.asarray(res.state["T"]), ref)
    out = igg.load_checkpoint(res.checkpoint)
    got = np.asarray(jnp.moveaxis(out["T"], -1, 0))
    np.testing.assert_array_equal(got, ref)      # lane 1 healthy on disk


def test_steps_per_call_folds_dispatches(tmp_path):
    """steps_per_call folds k steps into one compiled dispatch (an
    in-program fori_loop); cadences count steps and results match the
    one-step-per-dispatch run bit-exactly."""
    _grid()
    step = ensemble_member_step()
    states = ensemble_states(3)
    ref = np.asarray(_clean(step, states, 20).state["T"])
    res = igg.run_ensemble(step, states, 20, watch_every=10,
                           checkpoint_dir=tmp_path, checkpoint_every=10,
                           steps_per_call=5)
    assert res.steps_done == 20
    np.testing.assert_array_equal(np.asarray(res.state["T"]), ref)
