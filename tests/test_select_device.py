"""Device selection (`/root/reference/test/test_select_device.jl`).

The reference's test is two-sided: a valid id is returned when a device is
available, and an error is raised when it is not (`:17-26`).  Here the happy
path runs for real; the decision logic (`igg.device._select`) is additionally
unit-tested across deployment shapes — including the over-subscription error,
which needs more processes on a host than the host has devices and so cannot
be constructed with real virtual-CPU processes (each process always brings
its own devices).
"""

import pytest

import igg
from igg.device import _select


def test_select_device_returns_valid_id():
    import jax

    igg.init_global_grid(6, 6, 6, quiet=True)
    dev_id = igg.select_device()
    assert dev_id in [d.id for d in jax.local_devices()]


def test_select_device_requires_initialized_grid():
    with pytest.raises(igg.GridError, match="init_global_grid"):
        igg.select_device()


def test_single_process_per_host_owning_all_chips():
    # 1 process, 4 chips: bind the first local device.
    assert _select(0, 1, 4, 4) == 0


def test_one_device_per_process_deployment():
    # 4 processes on a 4-chip host, each owning one (disjoint) chip: every
    # rank binds its only local device — never an over-subscription error
    # (devices are disjoint per process in JAX, unlike MPI+CUDA where all
    # ranks see all node GPUs).
    for me_l in range(4):
        assert _select(me_l, 4, 1, 4) == 0


def test_processes_sharing_visible_devices():
    # 2 processes on one host, each seeing 4 (virtual) devices: node-local
    # rank picks distinct devices.
    assert _select(0, 2, 4, 8) == 0
    assert _select(1, 2, 4, 8) == 1


def test_oversubscribed_host_raises():
    # 3 processes on a 2-device host: the reference's "more processes than
    # GPUs per node" error (`/root/reference/src/select_device.jl:18`).
    with pytest.raises(igg.GridError, match="runs 3 processes"):
        _select(2, 3, 1, 2)


def test_no_devices_raises():
    with pytest.raises(igg.GridError, match="no JAX devices"):
        _select(0, 1, 0, 0)
