"""Fused Pallas HM3D step vs the XLA composition (interpret mode).

Same contract as the Stokes kernel test: identical `step_core` arithmetic,
so the two paths agree to float32 rounding (x halo planes are recomputed
from thin windows — ~1-2 ulp reassociation differences expected)."""

import numpy as np
import pytest

import igg
from igg.models import hm3d


@pytest.fixture
def selfwrap_grid():
    igg.init_global_grid(16, 8, 8, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    yield igg.get_global_grid()
    igg.finalize_global_grid()


def _fields():
    import jax.numpy as jnp

    params = hm3d.Params()
    Pe, phi = hm3d.init_fields(params, dtype=np.float32)
    r = jnp.arange(Pe.size, dtype=np.float32).reshape(Pe.shape)
    return (0.1 * jnp.sin(r), params.phi0 * (1.2 + 0.3 * jnp.cos(r * 0.7)))


def test_supported(selfwrap_grid):
    import jax

    from igg.ops import hm3d_pallas_supported

    Pe = jax.ShapeDtypeStruct((16, 8, 8), np.float32)
    assert hm3d_pallas_supported(selfwrap_grid, Pe)


def test_not_supported_open_boundary():
    import jax

    from igg.ops import hm3d_pallas_supported

    igg.init_global_grid(16, 8, 8, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, quiet=True)
    Pe = jax.ShapeDtypeStruct((16, 8, 8), np.float32)
    assert not hm3d_pallas_supported(igg.get_global_grid(), Pe)
    igg.finalize_global_grid()


@pytest.mark.parametrize("steps", [1, 3])
def test_matches_xla_path(selfwrap_grid, steps):
    params = hm3d.Params()
    dx, dy, dz = params.spacing()
    dt = params.timestep()
    kw = dict(dx=dx, dy=dy, dz=dz, dt=dt, phi0=params.phi0,
              npow=params.npow, eta=params.eta)

    Pe0, phi0_ = _fields()
    # exchange-fresh start (both paths consume halos identically, but a
    # physical state is the honest comparison)
    Pe0, phi0_ = igg.update_halo(Pe0, phi0_)

    Pe_x, phi_x = Pe0, phi0_
    Pe_p, phi_p = Pe0, phi0_
    for _ in range(steps):
        Pe_x, phi_x = hm3d.local_step(Pe_x, phi_x, **kw)
        Pe_p, phi_p = hm3d.local_step(Pe_p, phi_p, **kw, use_pallas=True,
                                      pallas_interpret=True)
    for a, b, name in ((Pe_x, Pe_p, "Pe"), (phi_x, phi_p, "phi")):
        a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
        scale = max(np.abs(a).max(), 1e-30)
        assert np.abs(a - b).max() <= 4e-6 * scale, name


def test_wrong_config_raises(selfwrap_grid):
    params = hm3d.Params()
    dx, dy, dz = params.spacing()
    Pe, phi = _fields()
    with pytest.raises(igg.GridError, match="fused HM3D"):
        hm3d.local_step(Pe, phi, dx=dx, dy=dy, dz=dz,
                        dt=params.timestep(), phi0=params.phi0,
                        npow=params.npow, eta=params.eta,
                        overlap=True, use_pallas=True,
                        pallas_interpret=True)


def _mesh_fields():
    params = hm3d.Params(lx=4.0, ly=4.0, lz=60.0)
    Pe, phi = hm3d.init_fields(params, dtype=np.float32)
    return params, Pe, phi


def test_pallas_sharded_mesh_periodic_matches_xla_path():
    """VERDICT round-3 item 1: the fused HM3D step on a SHARDED mesh (8 CPU
    devices, interpret mode) must reproduce the portable shard_map/XLA
    path.  Fully periodic, so the overlap-style exchange is bit-equivalent
    to the sequential composition."""
    igg.init_global_grid(8, 8, 128, periodx=1, periody=1, periodz=1,
                         quiet=True)
    assert igg.get_global_grid().nprocs == 8
    params, Pe, phi = _mesh_fields()
    xla = hm3d.make_step(params, donate=False, use_pallas=False)
    pal = hm3d.make_step(params, donate=False, use_pallas=True,
                         pallas_interpret=True)
    Sx, Sp = (Pe, phi), (Pe, phi)
    for _ in range(3):
        Sx = xla(*Sx)
        Sp = pal(*Sp)
    for a, b, name in ((Sx[0], Sp[0], "Pe"), (Sx[1], Sp[1], "phi")):
        a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
        scale = max(np.abs(a).max(), 1e-30)
        assert np.abs(a - b).max() <= 4e-6 * scale, name
    igg.finalize_global_grid()


def test_pallas_sharded_mesh_open_boundaries_matches_overlap_path():
    """Open boundaries on a sharded mesh: the fused step has
    hide_communication semantics, so it must match the overlap=True XLA
    path (including the stale-halo no-write behavior at edge devices)."""
    igg.init_global_grid(8, 8, 128, quiet=True)   # open bnds, 8 devices
    params, Pe, phi = _mesh_fields()
    ref = hm3d.make_step(params, donate=False, use_pallas=False,
                         overlap=True)
    pal = hm3d.make_step(params, donate=False, use_pallas=True,
                         pallas_interpret=True)
    Sr, Sp = (Pe, phi), (Pe, phi)
    for _ in range(3):
        Sr = ref(*Sr)
        Sp = pal(*Sp)
    for a, b, name in ((Sr[0], Sp[0], "Pe"), (Sr[1], Sp[1], "phi")):
        a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
        scale = max(np.abs(a).max(), 1e-30)
        assert np.abs(a - b).max() <= 4e-6 * scale, name
    igg.finalize_global_grid()


def test_pallas_slab_carry_multi_step_matches_overlap_path():
    """The slab-carry steady state (`igg.ops.fused_hm3d_steps`): only
    n_inner > 1 exercises steps whose send-plane slabs came from the
    kernel, on both periodic and open-boundary sharded meshes."""
    for periods in (dict(periodx=1, periody=1, periodz=1), {}):
        igg.init_global_grid(8, 8, 128, quiet=True, **periods)
        params, Pe, phi = _mesh_fields()
        ref = hm3d.make_step(params, donate=False, use_pallas=False,
                             overlap=True, n_inner=4)
        pal = hm3d.make_step(params, donate=False, use_pallas=True,
                             pallas_interpret=True, n_inner=4)
        Sr = ref(Pe, phi)
        Sp = pal(Pe, phi)
        for a, b, name in ((Sr[0], Sp[0], "Pe"), (Sr[1], Sp[1], "phi")):
            a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
            scale = max(np.abs(a).max(), 1e-30)
            assert np.abs(a - b).max() <= 2e-5 * scale, (name, periods)
        igg.finalize_global_grid()


def test_pallas_mixed_wrap_meshes_match_overlap_path():
    """Per-dimension halo modes on the practical 1-D/2-D decompositions
    `(N,1,1)`/`(N,M,1)`/`(1,M,1)`: wrapped dims in-VMEM, exchanged dims via
    the engine, mixed periodicity."""
    configs = [
        dict(dimx=4, dimy=2, dimz=1, periodz=1, periodx=1),
        dict(dimx=8, dimy=1, dimz=1, periody=1, periodz=1),
        dict(dimx=1, dimy=8, dimz=1, periodx=1, periody=1, periodz=1),
    ]
    for kw_grid in configs:
        igg.init_global_grid(8, 8, 128, quiet=True, **kw_grid)
        params, Pe, phi = _mesh_fields()
        ref = hm3d.make_step(params, donate=False, use_pallas=False,
                             overlap=True, n_inner=3)
        pal = hm3d.make_step(params, donate=False, use_pallas=True,
                             pallas_interpret=True, n_inner=3)
        Sr = ref(Pe, phi)
        Sp = pal(Pe, phi)
        for a, b, name in ((Sr[0], Sp[0], "Pe"), (Sr[1], Sp[1], "phi")):
            a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
            scale = max(np.abs(a).max(), 1e-30)
            assert np.abs(a - b).max() <= 2e-5 * scale, (name, kw_grid)
        igg.finalize_global_grid()


def test_make_step_pallas_interpret(selfwrap_grid):
    """The sharded make_step wrapper (not just local_step) must run the
    fused path in interpret mode — pins the check_vma workaround."""
    params = hm3d.Params()
    Pe, phi = _fields()
    step = hm3d.make_step(params, use_pallas=True, pallas_interpret=True,
                          donate=False)
    ref = hm3d.make_step(params, donate=False, use_pallas=False)
    Pe2, phi2 = step(Pe, phi)
    Pe3, phi3 = ref(Pe, phi)
    for a, b in ((Pe2, Pe3), (phi2, phi3)):
        a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
        scale = max(np.abs(a).max(), 1e-30)
        assert np.abs(a - b).max() <= 4e-6 * scale
