"""Communication observability (igg/comm.py) and its round-14
satellites: the (dim, mode)-labeled plane-bytes counters reconciling
against the analytic model, the comm ledger + ICI roofline gauges, the
step-time decomposition (AOT and in-run), the collective-stall
heartbeat fired deterministically through the chaos probe-fetch seam,
per-rank skew + merge-tool clock offsets, hide_communication
span/metric wiring, and the `python -m igg.comm report` CLI."""

import json
import pathlib
import time

import numpy as np
import pytest

import igg
from igg import comm as icomm
from igg import telemetry as tel


@pytest.fixture(autouse=True)
def _clean_observability():
    """Metrics, the flight ring, and the perf ledger are process-global;
    isolate every test (the test_telemetry fixture's pattern)."""
    tel.reset_metrics()
    tel._ring().clear()
    igg.perf.reset()
    yield
    for s in list(tel._SESSIONS):
        s.detach()
    tel.reset_metrics()
    igg.perf.reset()


def _grid(**kw):
    args = dict(periodx=1, periody=1, periodz=1, quiet=True)
    args.update(kw)
    igg.init_global_grid(6, 6, 6, **args)


def _compute(T):
    from igg.ops import interior_add

    lap = (T[:-2, 1:-1, 1:-1] + T[2:, 1:-1, 1:-1]
           + T[1:-1, :-2, 1:-1] + T[1:-1, 2:, 1:-1]
           + T[1:-1, 1:-1, :-2] + T[1:-1, 1:-1, 2:]
           - 6.0 * T[1:-1, 1:-1, 1:-1])
    return interior_add(T, 0.1 * lap)


def _make_step():
    @igg.sharded
    def step(T):
        return igg.update_halo_local(_compute(T))

    return lambda st: {"T": step(st["T"])}


def _init_state(seed=3):
    rng = np.random.default_rng(seed)
    T = igg.from_local_blocks(lambda c, ls: rng.standard_normal(ls),
                              (6, 6, 6))
    return {"T": igg.update_halo(T)}


def _counter_value(name_key):
    return tel.snapshot().get(name_key, {}).get("value", 0.0)


# ---------------------------------------------------------------------------
# (i) labeled plane-bytes counters + the analytic model
# ---------------------------------------------------------------------------

def test_plane_bytes_counter_reconciles_against_model():
    """One grouped update_halo advances the unlabeled total by exactly
    the analytic model, and the (dim, mode) breakdown sums to it —
    wire mode on the fully-split (2,2,2) mesh."""
    _grid()
    T = igg.zeros((6, 6, 6), dtype=np.float32) + 1.0
    before = _counter_value("igg_halo_plane_bytes_total")
    T = igg.update_halo(T)
    delta = _counter_value("igg_halo_plane_bytes_total") - before
    total, by_mode = icomm.plane_bytes_model((6, 6, 6), np.float32)
    assert delta == total > 0
    assert set(by_mode) == {("x", "wire_grouped"), ("y", "wire_grouped"),
                            ("z", "wire_grouped")}
    labeled = sum(
        _counter_value(f'igg_halo_plane_bytes_total{{dim="{d}",'
                       f'mode="{m}"}}') for d, m in by_mode)
    assert labeled == total


def test_plane_bytes_local_mode_on_unsplit_periodic_dim():
    """A single-device periodic dim is a self-wrap copy — mode 'local',
    not 'wire' — and the unlabeled total still counts it (dashboard
    continuity)."""
    _grid(dimx=4, dimy=2, dimz=1)
    total, by_mode = icomm.plane_bytes_model((6, 6, 6), np.float32)
    assert by_mode[("z", "local_grouped")] > 0
    assert by_mode[("x", "wire_grouped")] > 0
    T = igg.zeros((6, 6, 6)) + 1.0
    before = _counter_value("igg_halo_plane_bytes_total")
    igg.update_halo(T)
    assert (_counter_value("igg_halo_plane_bytes_total") - before
            == total)
    assert _counter_value('igg_halo_plane_bytes_total{dim="z",'
                          'mode="local_grouped"}') == \
        by_mode[("z", "local_grouped")]


def test_plane_bytes_stacked_mode_classification():
    """>= 2 same-shaped lane-active pair-emulated fields classify as the
    stacked group program (the `_stacked_lane64_update` election,
    engaged on CPU via the `_FORCE_STACKED64` seam) — and a single f64
    field stays 'grouped'."""
    from igg import halo

    _grid()
    grid = igg.get_global_grid()
    halo._FORCE_STACKED64 = True
    try:
        by2 = halo.plane_bytes_by_mode([(6, 6, 6)] * 2,
                                       [np.float64] * 2, grid)
        assert set(m for _, m in by2) == {"wire_stacked"}
        by1 = halo.plane_bytes_by_mode([(6, 6, 6)], [np.float64], grid)
        assert set(m for _, m in by1) == {"wire_grouped"}
        # The counters agree with the engine actually running the
        # stacked program.
        A = igg.zeros((6, 6, 6), dtype=np.float64) + 1.0
        B = igg.zeros((6, 6, 6), dtype=np.float64) + 2.0
        before = _counter_value('igg_halo_plane_bytes_total{dim="x",'
                                'mode="wire_stacked"}')
        A, B = igg.update_halo(A, B)
        assert _counter_value('igg_halo_plane_bytes_total{dim="x",'
                              'mode="wire_stacked"}') > before
        assert np.isfinite(np.asarray(A)).all()
    finally:
        halo._FORCE_STACKED64 = False
        halo.free_update_halo_buffers()


# ---------------------------------------------------------------------------
# (ii) the comm ledger + ICI roofline gauges
# ---------------------------------------------------------------------------

def test_calibrate_comm_records_ledger_sample_and_gauges(tmp_path,
                                                         monkeypatch):
    _grid()
    monkeypatch.setenv("IGG_PERF_LEDGER", str(tmp_path / "ledger.json"))
    sample = icomm.calibrate_comm(nfields=2, n_inner=2, nt=2)
    assert sample["path"] == "grouped"
    assert sample["tier"] == "halo.xyz.grouped"
    assert sample["gbps"] > 0
    # CPU mesh: the ICI link peak is honestly None — no pct gauge lies.
    assert sample["link_peak_gbps"] is None
    assert sample["pct_link_peak"] is None
    snap = tel.snapshot()
    assert snap['igg_halo_gbps{path="grouped"}']["value"] == \
        pytest.approx(sample["gbps"])
    assert not any(k.startswith("igg_pct_link_peak") for k in snap)
    # The ledger's comm section: keyed on (dims, dtype, shape, path,
    # backend, device_kind), persisted through the PR-8 machinery.
    entries = igg.perf.query("comm")
    assert len(entries) == 1
    e = entries[0]
    assert e["tier"] == "halo.xyz.grouped"
    assert tuple(e["dims"]) == (2, 2, 2)
    assert e["backend"] == "cpu"
    assert igg.perf.save() is not None
    doc = json.loads((tmp_path / "ledger.json").read_text())
    assert any(v["family"] == "comm" for v in doc["entries"].values())
    # A comm_sample bus record landed in the flight ring.
    assert any(r.kind == "comm_sample" for r in tel.flight_recorder())


def test_link_peak_table_is_honest():
    assert icomm.link_peak_gbps("TPU v5p") == 600.0
    assert icomm.link_peak_gbps("TPU v5e") == 200.0
    assert icomm.link_peak_gbps("cpu") is None          # no invented peak
    assert icomm.link_peak_gbps("TPU v99x") is None     # unknown chip
    assert icomm.link_peak_gbps(None) is None


def test_calibrate_comm_returns_none_when_nothing_moves():
    """A single open-boundary device: no dim moves (both global edges
    live on the one device), so there is nothing to measure — None, not
    a zero-byte sample."""
    import jax

    igg.init_global_grid(6, 6, 6, periodx=0, periody=0, periodz=0,
                         quiet=True, devices=jax.devices()[:1])
    assert icomm.calibrate_comm(nfields=1, n_inner=2, nt=2) is None
    assert igg.perf.query("comm") == []


# ---------------------------------------------------------------------------
# (iii) step-time decomposition
# ---------------------------------------------------------------------------

def test_decompose_emits_comm_stats_and_fractions():
    _grid()
    state = _init_state()
    d = icomm.decompose(_compute, (state["T"],), nt=2, n_inner=3)
    assert d["compute_ms"] > 0 and d["exchange_ms"] > 0
    assert 0.0 <= d["exposed_comm_fraction"] <= 1.0
    if "overlap_efficiency" in d:
        assert 0.0 <= d["overlap_efficiency"] <= 1.0
    recs = [r for r in tel.flight_recorder() if r.kind == "comm_stats"]
    assert recs and recs[-1].payload["source"] == "calibrate"
    # The decomposition also lands in the comm ledger (overlap.* tiers).
    tiers = {e["tier"] for e in igg.perf.query("comm")}
    assert {"overlap.compute", "overlap.exchange",
            "overlap.hidden"} <= tiers


def test_step_decomposition_monitor_rides_run_resilient(tmp_path):
    _grid()
    state = _init_state()
    monitor = icomm.StepDecomposition(_compute, (state["T"],), reps=2)
    res = igg.run_resilient(_make_step(), state, 120, watch_every=2,
                            telemetry=tmp_path, comm=monitor,
                            install_sigterm=False)
    assert res.steps_done == 120
    assert monitor.windows >= 1
    recs = [json.loads(l) for l in
            (tmp_path / "events_r0.jsonl").read_text().splitlines()]
    stats = [r for r in recs if r["kind"] == "comm_stats"]
    assert len(stats) == monitor.windows
    for r in stats:
        p = r["payload"]
        assert p["source"] == "probe"
        assert 0.0 <= p["exposed_comm_fraction"] <= 1.0
        assert p["compute_ms"] > 0 and p["hidden_ms"] > 0
    snap = tel.snapshot()
    assert 'igg_exposed_comm_fraction{run="resilient"}' in snap


def test_comm_monitor_requires_watch_cadence():
    _grid()
    state = _init_state()
    monitor = icomm.StepDecomposition(_compute, (state["T"],), reps=2)
    with pytest.raises(igg.GridError, match="watch cadence"):
        igg.run_resilient(_make_step(), state, 4, watch_every=0,
                          comm=monitor, install_sigterm=False)
    with pytest.raises(igg.GridError, match="StepDecomposition"):
        igg.run_resilient(_make_step(), state, 4, watch_every=2,
                          comm="not-a-monitor", install_sigterm=False)


# ---------------------------------------------------------------------------
# (iv) the collective-stall heartbeat
# ---------------------------------------------------------------------------

def test_stall_watchdog_fires_deterministically_via_chaos(tmp_path,
                                                          monkeypatch):
    """The acceptance path: chaos-injected never-ready fetches through
    the probe-fetch seam -> the heartbeat reports the over-age in-flight
    probe as a `collective_stall` event + structured stall report +
    flight dump, and the run still completes (forced fetches retire the
    probes — only the readiness channel is stalled)."""
    monkeypatch.setenv("IGG_COMM_STALL_TIMEOUT", "0.05")
    _grid()
    state = _init_state()
    step_fn = _make_step()
    slow = lambda st: (time.sleep(0.004), step_fn(st))[1]
    with igg.chaos.collective_stall():
        res = igg.run_resilient(slow, state, 40, watch_every=5,
                                max_pending_probes=100,
                                telemetry=tmp_path, install_sigterm=False)
    assert res.steps_done == 40
    recs = [json.loads(l) for l in
            (tmp_path / "events_r0.jsonl").read_text().splitlines()]
    stalls = [r for r in recs if r["kind"] == "collective_stall"]
    assert len(stalls) == 1          # once per stall episode, not per probe
    p = stalls[0]["payload"]
    assert "watchdog probe" in p["in_flight"]
    assert p["age_s"] >= 0.05 and p["timeout_s"] == 0.05
    assert p["pending"] >= 1
    report = json.loads((tmp_path / "stall_r0.json").read_text())
    assert report["reason"] == "collective_stall"
    assert report["step"] == stalls[0]["step"]
    dumps = tel.flight_dumps(tmp_path, rank=0)
    assert dumps, list(tmp_path.iterdir())
    dump = json.loads(dumps[0].read_text())
    assert "collective_stall" in dump["reason"]
    assert any(r["kind"] == "collective_stall" for r in dump["events"])


def test_stall_watchdog_quiet_on_healthy_run(tmp_path, monkeypatch):
    """Default-on stall detection must be silent on a healthy run (and a
    ready-but-unfetched probe is a slow host, not a stall).  The timeout
    sits above any plausible CI-host window so the only way to fire is a
    genuine freeze."""
    monkeypatch.setenv("IGG_COMM_STALL_TIMEOUT", "30")
    _grid()
    res = igg.run_resilient(_make_step(), _init_state(), 30,
                            watch_every=5, telemetry=tmp_path,
                            install_sigterm=False)
    assert res.steps_done == 30
    recs = [json.loads(l) for l in
            (tmp_path / "events_r0.jsonl").read_text().splitlines()]
    assert not any(r["kind"] == "collective_stall" for r in recs)
    assert not (tmp_path / "stall_r0.json").exists()


def test_stall_watchdog_unit_check_and_heal():
    """Unit-level: an over-age not-ready entry fires once; a subsequent
    fetch re-arms; timeout <= 0 disables via the factory."""

    class NeverReady:
        def is_ready(self):
            return False

    sw = icomm.StallWatchdog(0.01, run="unit", poll_s=10.0)  # no thread race
    try:
        sw.watch("a", 5, "unit probe", NeverReady())
        assert not sw.check(now=time.monotonic())   # not over-age yet
        time.sleep(0.02)
        assert sw.check()                           # fires
        assert sw.stalls == 1
        assert not sw.check()                       # once per episode
        sw.fetched("a", 5)                          # heals
        sw.watch("b", 7, "unit probe", NeverReady())
        time.sleep(0.02)
        assert sw.check() and sw.stalls == 2
        sw.fetched("b", 7)
        # Ready-but-unfetched is not a stall.
        sw.watch("c", 9, "unit probe", np.float32(1.0))
        time.sleep(0.02)
        assert not sw.check()
    finally:
        sw.close()
    assert icomm.make_stall_watchdog("x") is not None      # default on


def test_stall_watchdog_rearm_across_back_to_back_episodes(tmp_path):
    """The once-per-episode contract the heal loop depends on (round-15
    satellite): two stalls separated by a FULL channel drain produce
    exactly two `collective_stall` events and two stall reports — never
    one (a dead re-arm would starve the second heal action) and never
    three (a mid-drain double report would burn heal budget on one
    fault).  A partial drain (one of two in-flight entries retired) must
    NOT re-arm."""

    class NeverReady:
        def is_ready(self):
            return False

    sess = tel.Telemetry(tmp_path).attach()
    sw = icomm.StallWatchdog(0.01, run="unit", poll_s=10.0)  # manual beats
    try:
        # Episode 1: two in-flight entries, over-age -> fires ONCE.
        sw.watch("a", 5, "unit probe a", NeverReady())
        sw.watch("b", 7, "unit probe b", NeverReady())
        time.sleep(0.02)
        assert sw.check() and not sw.check()
        report1 = json.loads((tmp_path / "stall_r0.json").read_text())
        assert report1["step"] == 5
        # Partial drain: one entry retired, one still in flight — the
        # episode is NOT over, a new over-age check stays silent.
        sw.fetched("a", 5)
        time.sleep(0.02)
        assert not sw.check()
        # FULL drain ends the episode and re-arms.
        sw.fetched("b", 7)
        # Episode 2: a fresh stall fires again, with a fresh report.
        sw.watch("c", 11, "unit probe c", NeverReady())
        time.sleep(0.02)
        assert sw.check() and not sw.check()
        assert sw.stalls == 2
    finally:
        sw.close()
        sess.detach()
    recs = [json.loads(l) for l in
            (tmp_path / "events_r0.jsonl").read_text().splitlines()]
    stalls = [r for r in recs if r["kind"] == "collective_stall"]
    assert [r["step"] for r in stalls] == [5, 11]   # exactly two episodes
    report2 = json.loads((tmp_path / "stall_r0.json").read_text())
    assert report2["step"] == 11 and report2 != report1


def test_make_stall_watchdog_disabled_by_env(monkeypatch):
    monkeypatch.setenv("IGG_COMM_STALL_TIMEOUT", "0")
    assert icomm.make_stall_watchdog("x") is None


def test_collective_stall_seam_restores_on_exit():
    from igg import resilience

    class Obj:
        def is_ready(self):
            return True

    assert resilience._is_ready(Obj())
    with igg.chaos.collective_stall():
        assert resilience._CHAOS_FETCH_TAP is not None
        assert not resilience._is_ready(Obj())
    assert resilience._CHAOS_FETCH_TAP is None
    assert resilience._is_ready(Obj())


# ---------------------------------------------------------------------------
# (v) per-rank skew + merge-tool clock offsets
# ---------------------------------------------------------------------------

def _fake_rank_stream(path, process, rows):
    """rows: (wall, kind, step, payload)"""
    with open(path, "w") as fh:
        for wall, kind, step, payload in rows:
            fh.write(json.dumps({"t": wall, "wall": wall,
                                 "process": process, "kind": kind,
                                 "step": step, "payload": payload}) + "\n")


def test_rank_skew_worst_vs_median(tmp_path):
    for p, ms in ((0, 10.0), (1, 16.0), (2, 11.0)):
        _fake_rank_stream(
            tmp_path / f"events_r{p}.jsonl", p,
            [(100.0 + p, "step_stats", 50,
              {"ms_per_step": ms, "steps_per_s": 1e3 / ms}),
             (200.0 + p, "step_stats", 100,
              {"ms_per_step": ms + 1, "steps_per_s": 1e3 / (ms + 1)})])
    merged = tel.merge_streams([tmp_path])
    skew = icomm.rank_skew(merged)
    assert skew["ranks"] == [0, 1, 2]
    assert len(skew["per_step"]) == 2
    row = skew["per_step"][0]
    assert row["worst_rank"] == 1
    assert row["median_ms"] == 11.0
    assert row["skew_ms"] == pytest.approx(5.0)
    assert skew["max_skew_ms"] == pytest.approx(5.0)
    assert tel.snapshot()["igg_rank_skew_ms"]["value"] == \
        pytest.approx(5.0)
    # Single-rank streams: no skew, no crash.
    solo = [r for r in merged if r.get("process") == 0]
    assert icomm.rank_skew(solo)["per_step"] == []


def test_merge_summary_reports_rank_wall_offsets(tmp_path):
    """Rank 1's clock runs 5 s ahead: the merge summary's offset
    estimate recovers it as the median pairwise delta on matching-step
    records."""
    _fake_rank_stream(tmp_path / "events_r0.jsonl", 0,
                      [(100.0, "checkpoint", 10, {}),
                       (200.0, "checkpoint", 20, {}),
                       (300.0, "step_stats", 30, {"ms_per_step": 1.0})])
    _fake_rank_stream(tmp_path / "events_r1.jsonl", 1,
                      [(105.2, "checkpoint", 10, {}),
                       (204.9, "checkpoint", 20, {}),
                       (305.0, "step_stats", 30, {"ms_per_step": 2.0})])
    merged = tel.merge_streams([tmp_path])
    summary = merged[-1]
    assert summary["kind"] == "merge_summary"
    offs = summary["payload"]["rank_wall_offsets"]
    assert offs["1"] == pytest.approx(5.0, abs=0.3)
    assert summary["payload"]["offset_matched_records"] == 3
    # Single-rank merge: no offsets, and (with no skipped lines) no
    # summary record at all — the round-12 contract unchanged.
    solo = tel.merge_streams([tmp_path / "events_r0.jsonl"])
    assert all(r["kind"] != "merge_summary" for r in solo)


def test_step_stats_sets_rank_window_gauge():
    stats = tel.StepStats("unit")
    stats.fetched(10, 10)
    time.sleep(0.002)
    stats.fetched(20, 20)
    snap = tel.snapshot()
    assert snap['igg_rank_window_ms{run="unit"}']["value"] > 0


# ---------------------------------------------------------------------------
# (vi) hide_communication span/metric wiring
# ---------------------------------------------------------------------------

def test_hide_communication_telemetry_wiring():
    """Tracing a hide_communication program emits the bus record, the
    trace counter, and a span — and the restructured step still matches
    the plain composition on the 8-device interpret mesh."""
    _grid()
    state = _init_state()

    @igg.sharded
    def hidden_step(T):
        return igg.hide_communication(T, _compute)

    before = _counter_value("igg_hide_communication_traces_total")
    out = hidden_step(state["T"])
    assert (_counter_value("igg_hide_communication_traces_total")
            - before) >= 1
    recs = [r for r in tel.flight_recorder()
            if r.kind == "hide_communication"]
    assert recs and recs[-1].payload["n_fields"] == 1
    assert recs[-1].payload["radius"] == 1
    assert recs[-1].payload["dims"] == [0, 1, 2]
    spans = [r for r in tel.flight_recorder() if r.kind == "span"
             and r.payload.get("name") == "overlap.hide_communication"]
    assert spans

    @igg.sharded
    def plain_step(T):
        return igg.update_halo_local(_compute(T))

    # Numerical, not bitwise: the slab and full-domain programs may
    # fuse/FMA-contract differently (the test_overlap contract).
    ref = plain_step(state["T"])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# (vii) the report CLI
# ---------------------------------------------------------------------------

def test_report_cli_renders_ledger_decomposition_skew_and_stalls(
        tmp_path, capsys):
    _grid()
    icomm.calibrate_comm(nfields=1, n_inner=2, nt=2)
    rows = [(100.0, "step_stats", 50, {"ms_per_step": 10.0}),
            (150.0, "comm_stats", 60,
             {"source": "probe", "compute_ms": 1.0, "exchange_ms": 2.0,
              "hidden_ms": 1.5, "exposed_comm_fraction": 0.5,
              "overlap_efficiency": 0.5}),
            (180.0, "collective_stall", 70,
             {"in_flight": "watchdog probe", "age_s": 1.2,
              "timeout_s": 1.0, "last_completed_step": 65,
              "pending": 2})]
    _fake_rank_stream(tmp_path / "events_r0.jsonl", 0, rows)
    _fake_rank_stream(tmp_path / "events_r1.jsonl", 1,
                      [(100.5, "step_stats", 50, {"ms_per_step": 14.0})])
    rc = icomm._main(["report", str(tmp_path)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "comm ledger" in out and "halo.xyz.grouped" in out
    assert "step-time decomposition" in out and "0.500" in out
    # Two ranks at step 50 (10 vs 14 ms): even-count median 12, skew 2.
    assert "rank skew" in out and "max skew: 2.0000 ms" in out
    assert "collective stalls (1)" in out and "watchdog probe" in out
    # Usage errors exit 2.
    assert icomm._main([]) == 2
    assert icomm._main(["report", "--ledger"]) == 2


def test_comm_env_knob_registered():
    from igg import _env

    assert "IGG_COMM_STALL_TIMEOUT" in _env._KNOWN
