"""The resilience tier (igg/resilience.py, igg/chaos.py, and the round-8
checkpoint hardening) on the 8-device CPU mesh: every detection and
recovery path of the resilient run loop is PROVEN through the
deterministic fault injectors — NaN seeded at a step, halo-plane
corruption through the `igg.halo` test seam, checkpoint truncation and
bit-flip, simulated preemption — not just argued.  Plus the round-8
satellites: `jax.distributed.initialize` retry/backoff, stale `.tmp`
sweep, and the CRC32 checkpoint manifest."""

import os
import pathlib

import numpy as np
import pytest

import igg


# ---------------------------------------------------------------------------
# Harness: a deterministic sharded diffusion-like step over a dict state.
# ---------------------------------------------------------------------------

def _grid(**kw):
    args = dict(periodx=1, periody=1, periodz=1, quiet=True)
    args.update(kw)
    igg.init_global_grid(6, 6, 6, **args)          # (2,2,2) mesh


def _make_step():
    from igg.ops import interior_add

    @igg.sharded
    def step(T):
        lap = (T[:-2, 1:-1, 1:-1] + T[2:, 1:-1, 1:-1]
               + T[1:-1, :-2, 1:-1] + T[1:-1, 2:, 1:-1]
               + T[1:-1, 1:-1, :-2] + T[1:-1, 1:-1, 2:]
               - 6.0 * T[1:-1, 1:-1, 1:-1])
        return igg.update_halo_local(interior_add(T, 0.1 * lap))

    return lambda st: {"T": step(st["T"])}


def _init_state(seed=3):
    rng = np.random.default_rng(seed)
    T = igg.from_local_blocks(lambda c, ls: rng.standard_normal(ls),
                              (6, 6, 6))
    return {"T": igg.update_halo(T)}


def _clean_run(step_fn, state, n):
    for _ in range(n):
        state = step_fn(state)
    return np.asarray(state["T"])


# ---------------------------------------------------------------------------
# (i) detection: an injected NaN at step k is caught within one watch window
# ---------------------------------------------------------------------------

def test_nan_detected_within_one_watch_window(tmp_path):
    _grid()
    step_fn = _make_step()
    k = 7
    plan = igg.chaos.ChaosPlan(nan_at=[(k, "T")])
    res = igg.run_resilient(step_fn, _init_state(), 20, watch_every=5,
                            checkpoint_dir=tmp_path, checkpoint_every=5,
                            chaos=plan)
    det = [e for e in res.events if e.kind == "nan_detected"]
    assert len(det) == 1
    # The probe that catches it is within one watch window of the injection.
    assert k < det[0].step <= k + 5
    assert det[0].detail["counts"]["T"] > 0
    assert res.retries == 1
    assert np.isfinite(np.asarray(res.state["T"])).all()


def test_probe_counts_are_per_field_and_replicated(tmp_path):
    """Two watched fields: only the poisoned one reports a nonzero psum'd
    count (the probe is per-field, and a single bad element on ONE device
    is visible in the replicated full-mesh reduction)."""
    _grid()
    from igg.ops import interior_add

    @igg.sharded
    def step2(T, U):
        return (igg.update_halo_local(interior_add(T, 0.0 * T[1:-1, 1:-1,
                                                              1:-1])),
                igg.update_halo_local(interior_add(U, 0.0 * U[1:-1, 1:-1,
                                                              1:-1])))

    state = {"T": _init_state()["T"], "U": _init_state(5)["T"]}
    step_fn = lambda st: dict(zip(("T", "U"), step2(st["T"], st["U"])))
    # Poison U's interior on the LAST device's block (global index into
    # block (1,1,1) of the (2,2,2) mesh).
    plan = igg.chaos.ChaosPlan(nan_at=[(3, "U", (7, 7, 7))])
    res = igg.run_resilient(step_fn, state, 10, watch_every=5,
                            checkpoint_dir=tmp_path, checkpoint_every=5,
                            chaos=plan)
    det = [e for e in res.events if e.kind == "nan_detected"]
    assert det and "U" in det[0].detail["counts"]
    assert "T" not in det[0].detail["counts"]


# ---------------------------------------------------------------------------
# (ii) rollback + retry reproduces a clean run bit-exactly
# ---------------------------------------------------------------------------

def test_rollback_and_retry_bit_exact(tmp_path):
    _grid()
    step_fn = _make_step()
    ref = _clean_run(step_fn, _init_state(), 20)

    plan = igg.chaos.ChaosPlan(nan_at=[(7, "T")])
    res = igg.run_resilient(step_fn, _init_state(), 20, watch_every=5,
                            checkpoint_dir=tmp_path, checkpoint_every=5,
                            ring=3, chaos=plan)
    assert res.retries == 1 and res.steps_done == 20
    kinds = [e.kind for e in res.events]
    assert "rollback" in kinds
    np.testing.assert_array_equal(np.asarray(res.state["T"]), ref)
    # Ring pruned to `ring` newest generations — sharded DIRECTORIES now
    # (the run_resilient default), not flat .npz files.
    from igg.checkpoint import list_generations
    gens = list_generations(tmp_path)
    assert len(gens) == 3
    assert all(p.is_dir() for _, p in gens)


def test_fresh_run_clears_leftover_generations(tmp_path):
    """A fresh run (resume=False) into a directory holding generations from
    a PREVIOUS run must clear them and write its own entry generation —
    rolling back into another run's state (at step 0 OR mid-run) would be
    silently wrong results."""
    _grid()
    step_fn = _make_step()
    # A previous run leaves DIFFERENT states as generations 0 and 5; the
    # one at 5 would otherwise be the preferred (newest <= failure step)
    # rollback target.
    other = _init_state(seed=99)
    igg.save_checkpoint(tmp_path / "ckpt_000000000.npz", **other)
    igg.save_checkpoint(tmp_path / "ckpt_000000005.npz", **other)

    state0 = _init_state()
    ref = _clean_run(step_fn, dict(state0), 10)
    plan = igg.chaos.ChaosPlan(nan_at=[(2, "T")])
    res = igg.run_resilient(step_fn, state0, 10, watch_every=5,
                            checkpoint_dir=tmp_path, checkpoint_every=10,
                            chaos=plan)
    # Detection at 5 rolled back to generation 0 — THIS run's initial
    # state; the foreign generation 5 was cleared at entry, never loaded.
    rb = next(e for e in res.events if e.kind == "rollback")
    assert rb.step == 0
    assert res.retries == 1
    np.testing.assert_array_equal(np.asarray(res.state["T"]), ref)


def test_ring_ignores_sibling_prefix(tmp_path):
    """A sibling ring sharing the directory under a longer prefix is
    neither pruned nor rolled back into."""
    _grid()
    step_fn = _make_step()
    foreign = tmp_path / "ckpt_b_000000099.npz"
    igg.save_checkpoint(foreign, **_init_state(seed=7))
    igg.run_resilient(step_fn, _init_state(), 20, watch_every=5,
                      checkpoint_dir=tmp_path, checkpoint_every=5, ring=2)
    assert foreign.exists()      # ring=2 pruning never touched it
    assert igg.latest_checkpoint(tmp_path).name == "ckpt_000000020"


def test_rollback_skips_poisoned_generation(tmp_path):
    """A generation written between the blowup and its detection is
    structurally valid but holds NaNs; rollback must skip it (check_finite)
    and land on the older healthy one."""
    _grid()
    step_fn = _make_step()
    ref = _clean_run(step_fn, _init_state(), 20)
    # checkpoint_every=2 < watch_every=10: gens at 8 and 10 are written
    # AFTER the step-7 injection but before the step-10 probe is fetched.
    plan = igg.chaos.ChaosPlan(nan_at=[(7, "T")])
    res = igg.run_resilient(step_fn, _init_state(), 20, watch_every=10,
                            checkpoint_dir=tmp_path, checkpoint_every=2,
                            ring=10, max_pending_probes=4, chaos=plan)
    rb = [e for e in res.events if e.kind == "rollback"]
    assert rb and rb[0].step <= 6      # not the poisoned 8/10 generations
    np.testing.assert_array_equal(np.asarray(res.state["T"]), ref)


def test_ring_prune_protects_last_healthy_generation(tmp_path):
    """With checkpoint_every << watch_every, every generation in the ring
    can be poisoned before the first probe lands; plain newest-R pruning
    would rotate the only healthy rollback target (the entry generation)
    out.  The prune must keep the newest probe-confirmed generation."""
    _grid()
    step_fn = _make_step()
    ref = _clean_run(step_fn, _init_state(), 20)
    # NaN at step 1: gens 2,4,6,8,10 are all poisoned; ring=2 would have
    # pruned the healthy gen 0 by the time the step-10 probe detects.
    plan = igg.chaos.ChaosPlan(nan_at=[(1, "T")])
    res = igg.run_resilient(step_fn, _init_state(), 20, watch_every=10,
                            checkpoint_dir=tmp_path, checkpoint_every=2,
                            ring=2, chaos=plan)
    rb = [e for e in res.events if e.kind == "rollback"]
    assert rb and rb[0].step == 0        # recovered via the protected gen
    np.testing.assert_array_equal(np.asarray(res.state["T"]), ref)


def test_chaos_injection_inside_multi_step_dispatch(tmp_path):
    """An injection step inside a steps_per_call window fires at the
    dispatch boundary before it instead of silently never firing."""
    _grid()
    base = _make_step()

    def step5(st):
        for _ in range(5):
            st = base(st)
        return st

    ref = _clean_run(base, _init_state(), 20)
    plan = igg.chaos.ChaosPlan(nan_at=[(7, "T")])   # 7 not a call boundary
    res = igg.run_resilient(step5, _init_state(), 20, watch_every=10,
                            checkpoint_dir=tmp_path, checkpoint_every=10,
                            steps_per_call=5, chaos=plan)
    inj = [e for e in res.events if e.kind == "chaos_nan"]
    assert inj and inj[0].step == 5      # the boundary before step 7
    assert any(e.kind == "nan_detected" for e in res.events)
    assert res.retries == 1
    np.testing.assert_array_equal(np.asarray(res.state["T"]), ref)


# ---------------------------------------------------------------------------
# (iii) latest_checkpoint falls back past corrupt/truncated generations
# ---------------------------------------------------------------------------

def test_latest_checkpoint_falls_back_past_truncation(tmp_path):
    _grid()
    step_fn = _make_step()
    igg.run_resilient(step_fn, _init_state(), 15, watch_every=5,
                      checkpoint_dir=tmp_path, checkpoint_every=5, ring=3)
    newest = igg.latest_checkpoint(tmp_path)
    assert newest is not None and igg.checkpoint.checkpoint_step(newest) == 15

    igg.chaos.corrupt_checkpoint(newest, "truncate")   # truncates shard 0
    fallback = igg.latest_checkpoint(tmp_path)
    assert fallback is not None and igg.checkpoint.checkpoint_step(fallback) == 10
    # The truncated newest raises a GridError NAMING the path (not a raw
    # zipfile.BadZipFile), the satellite contract.
    with pytest.raises(igg.GridError, match=newest.name):
        igg.load_checkpoint(newest)
    # The fallback is loadable and the run continues from it.
    out = igg.load_checkpoint(fallback)
    assert np.isfinite(np.asarray(out["T"])).all()


def test_latest_checkpoint_falls_back_past_bitflip(tmp_path):
    """A bit-flip that keeps the zip container self-consistent is caught by
    the CRC32 manifest in `__igg_meta__` — the container's own checksums
    cannot see it."""
    _grid()
    step_fn = _make_step()
    igg.run_resilient(step_fn, _init_state(), 10, watch_every=5,
                      checkpoint_dir=tmp_path, checkpoint_every=5, ring=3)
    newest = igg.latest_checkpoint(tmp_path)
    igg.chaos.corrupt_checkpoint(newest, "bitflip", field="T")
    with pytest.raises(igg.GridError, match="CRC32 mismatch"):
        igg.load_checkpoint(newest)
    assert not igg.verify_checkpoint(newest)
    fallback = igg.latest_checkpoint(tmp_path)
    assert fallback is not None and fallback != newest


def test_crc32_manifest_roundtrip(tmp_path):
    _grid()
    state = _init_state()
    igg.save_checkpoint(tmp_path / "ck.npz", **state)
    # Manifest present and verified on a normal load.
    from igg import checkpoint as ckpt
    meta, arrays = ckpt._read_verified(tmp_path / "ck.npz")
    assert set(meta["crc32"]) == {"T"}
    assert igg.verify_checkpoint(tmp_path / "ck.npz", check_finite=True)
    out = igg.load_checkpoint(tmp_path / "ck.npz")
    np.testing.assert_array_equal(np.asarray(out["T"]),
                                  np.asarray(state["T"]))


def test_bf16_watched_and_health_gated(tmp_path):
    """Extension float dtypes (numpy kind 'V'): the default watch set must
    include a bfloat16 field and the checkpoint finite gate must reject a
    NaN-poisoned bf16 generation — a numpy-kind 'fc' test would silently
    wave both through."""
    import jax.numpy as jnp

    _grid()
    T = igg.zeros((6, 6, 6), dtype=jnp.bfloat16) + jnp.asarray(
        1.5, jnp.bfloat16)
    igg.save_checkpoint(tmp_path / "good.npz", T=T)
    assert igg.verify_checkpoint(tmp_path / "good.npz", check_finite=True)

    bad = T.at[(1, 1, 1)].set(jnp.asarray(float("nan"), jnp.bfloat16))
    igg.save_checkpoint(tmp_path / "bad.npz", T=bad)
    assert igg.verify_checkpoint(tmp_path / "bad.npz")
    assert not igg.verify_checkpoint(tmp_path / "bad.npz",
                                     check_finite=True)

    # And the watchdog: a bf16-only state is watched by default.
    @igg.sharded
    def hold(T):
        return igg.update_halo_local(T)

    plan = igg.chaos.ChaosPlan(nan_at=[(2, "T")])
    res = igg.run_resilient(lambda st: {"T": hold(st["T"])}, {"T": T}, 10,
                            watch_every=5, checkpoint_dir=tmp_path / "ring",
                            checkpoint_every=5, chaos=plan)
    assert any(e.kind == "nan_detected" for e in res.events)
    assert res.retries == 1


def test_rollback_discards_newer_abandoned_generations(tmp_path):
    """Generations newer than the rollback target belong to the abandoned
    attempt; a later resume must not land on them."""
    _grid()
    step_fn = _make_step()
    # checkpoint_every=2 << watch_every=10: poisoned gens 8 and 10 exist
    # when the step-10 probe detects the step-7 injection.
    plan = igg.chaos.ChaosPlan(nan_at=[(7, "T")], preempt_at=12)
    res = igg.run_resilient(step_fn, _init_state(), 20, watch_every=10,
                            checkpoint_dir=tmp_path, checkpoint_every=2,
                            ring=10, chaos=plan)
    rb = next(e for e in res.events if e.kind == "rollback")
    assert rb.step <= 6
    # Preempted at replay step 12; every surviving generation is at or
    # below it — the abandoned attempt's gens 8/10 were discarded at
    # rollback and rewritten by the replay.
    assert res.preempted and res.steps_done == 12
    from igg.checkpoint import list_generations
    steps = [s for s, _ in list_generations(tmp_path)]
    assert max(steps) == 12
    assert igg.latest_checkpoint(tmp_path, check_finite=True).name \
        == "ckpt_000000012"


# ---------------------------------------------------------------------------
# (iv) preemption leaves a loadable checkpoint; resume completes the run
# ---------------------------------------------------------------------------

def test_preemption_writes_final_checkpoint_and_resume(tmp_path):
    _grid()
    step_fn = _make_step()
    ref = _clean_run(step_fn, _init_state(), 20)

    plan = igg.chaos.ChaosPlan(preempt_at=12)
    res = igg.run_resilient(step_fn, _init_state(), 20, watch_every=5,
                            checkpoint_dir=tmp_path, checkpoint_every=5,
                            chaos=plan)
    assert res.preempted and res.steps_done == 12
    assert [e.kind for e in res.events].count("preempt") == 1
    # The final generation is at the preemption step, atomic and loadable.
    newest = igg.latest_checkpoint(tmp_path, check_finite=True)
    assert newest is not None and igg.checkpoint.checkpoint_step(newest) == 12
    assert igg.verify_checkpoint(newest)
    # Relaunch with resume=True: continues from 12 and matches the clean
    # run bit-exactly.
    res2 = igg.run_resilient(step_fn, _init_state(), 20, watch_every=5,
                             checkpoint_dir=tmp_path, checkpoint_every=5,
                             resume=True)
    assert not res2.preempted and res2.steps_done == 20
    assert res2.events[0].kind == "resume" and res2.events[0].step == 12
    np.testing.assert_array_equal(np.asarray(res2.state["T"]), ref)


def test_sigterm_handler_sets_preemption(tmp_path):
    """The installed SIGTERM handler drives the same path the chaos
    injector does: raise the signal from inside a step."""
    import signal

    _grid()
    base = _make_step()
    fired = {"done": False}

    def step_fn(st):
        out = base(st)
        if not fired["done"]:
            fired["done"] = True
            os.kill(os.getpid(), signal.SIGTERM)
        return out

    res = igg.run_resilient(step_fn, _init_state(), 20, watch_every=5,
                            checkpoint_dir=tmp_path, checkpoint_every=5)
    assert res.preempted and 0 < res.steps_done < 20
    assert igg.latest_checkpoint(tmp_path) is not None
    # The handler is restored and the flag cleared on exit.
    assert not igg.resilience.preemption_requested()


# ---------------------------------------------------------------------------
# Halo-plane corruption (the igg.halo test seam): detect AND recover
# ---------------------------------------------------------------------------

def test_halo_corruption_detected_and_recovered(tmp_path):
    _grid()
    step_fn = _make_step()
    ref = _clean_run(step_fn, _init_state(), 15)

    fault = igg.chaos.halo_corruption()
    seen = []

    def policy(attempt, state, ev):
        seen.append((attempt, ev.kind))
        fault.disarm()      # the transient interconnect fault heals
        return None

    state0 = _init_state()   # built clean, before the fault is armed
    fault.arm()
    try:
        res = igg.run_resilient(step_fn, state0, 15, watch_every=5,
                                checkpoint_dir=tmp_path, checkpoint_every=5,
                                recovery_policy=policy)
    finally:
        fault.disarm()
    assert seen == [(1, "nan_detected")]
    assert res.retries == 1
    np.testing.assert_array_equal(np.asarray(res.state["T"]), ref)


def test_persistent_fault_exhausts_retry_budget(tmp_path):
    _grid()
    step_fn = _make_step()
    fault = igg.chaos.halo_corruption()
    state0 = _init_state()   # built clean, before the fault is armed
    fault.arm()
    try:
        with pytest.raises(igg.ResilienceError, match="retry budget"):
            igg.run_resilient(step_fn, state0, 15, watch_every=5,
                              checkpoint_dir=tmp_path, checkpoint_every=5,
                              max_retries=2)
    finally:
        fault.disarm()


def test_detection_without_ring_fails_fast():
    _grid()
    step_fn = _make_step()
    plan = igg.chaos.ChaosPlan(nan_at=[(3, "T")])
    with pytest.raises(igg.ResilienceError, match="no checkpoint_dir"):
        igg.run_resilient(step_fn, _init_state(), 10, watch_every=5,
                          chaos=plan)


# ---------------------------------------------------------------------------
# Divergence predicate and recovery-policy step swap
# ---------------------------------------------------------------------------

def test_divergence_predicate_triggers_rollback(tmp_path):
    _grid()
    step_fn = _make_step()
    ref = _clean_run(step_fn, _init_state(), 20)
    fired = {"n": 0}

    def diverged(state):
        # One-shot predicate: flags the second watch boundary once — the
        # replay passes clean (a transient divergence judgement).
        fired["n"] += 1
        return fired["n"] == 2

    res = igg.run_resilient(step_fn, _init_state(), 20, watch_every=5,
                            checkpoint_dir=tmp_path, checkpoint_every=5,
                            divergence_fn=diverged)
    kinds = [e.kind for e in res.events]
    assert "divergence" in kinds and "rollback" in kinds
    assert res.retries == 1
    np.testing.assert_array_equal(np.asarray(res.state["T"]), ref)


def test_recovery_policy_may_swap_step_fn(tmp_path):
    """The documented dt-damping shape: the policy returns (state, new
    step_fn) and the retry runs the swapped step."""
    _grid()
    step_a = _make_step()
    calls = {"b": 0}

    def step_b(st):
        calls["b"] += 1
        return step_a(st)

    plan = igg.chaos.ChaosPlan(nan_at=[(3, "T")])
    res = igg.run_resilient(
        step_a, _init_state(), 10, watch_every=5,
        checkpoint_dir=tmp_path, checkpoint_every=5,
        recovery_policy=lambda k, st, ev: (st, step_b), chaos=plan)
    assert res.retries == 1
    rb = next(e for e in res.events if e.kind == "rollback")
    assert calls["b"] == 10 - rb.step    # the whole replay ran step_b


# ---------------------------------------------------------------------------
# Loop-contract validation
# ---------------------------------------------------------------------------

def test_cadence_validation():
    _grid()
    step_fn = _make_step()
    with pytest.raises(igg.GridError, match="steps_per_call"):
        igg.run_resilient(step_fn, _init_state(), 10, watch_every=5,
                          steps_per_call=3)
    with pytest.raises(igg.GridError, match="checkpoint_dir"):
        igg.run_resilient(step_fn, _init_state(), 10, checkpoint_every=5)
    with pytest.raises(igg.GridError, match="non-empty dict"):
        igg.run_resilient(step_fn, [], 10)
    with pytest.raises(igg.GridError, match="watch cadence"):
        igg.run_resilient(step_fn, _init_state(), 10, watch_every=0,
                          divergence_fn=lambda st: False)


def test_steps_per_call_multi_step_dispatch(tmp_path):
    """The TPU idiom: step_fn advances several steps per compiled dispatch;
    cadences count steps."""
    _grid()
    base = _make_step()

    def step5(st):
        for _ in range(5):
            st = base(st)
        return st

    ref = _clean_run(base, _init_state(), 20)
    res = igg.run_resilient(step5, _init_state(), 20, watch_every=10,
                            checkpoint_dir=tmp_path, checkpoint_every=10,
                            steps_per_call=5)
    assert res.steps_done == 20
    np.testing.assert_array_equal(np.asarray(res.state["T"]), ref)


# ---------------------------------------------------------------------------
# Sharded generations (round 9): distributed failure shapes, async writes,
# elastic resume
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["truncate", "bitflip", "missing_shard",
                                  "partial_commit", "preempt_mid_write"])
def test_sharded_fault_skipped_and_recovered_bit_exact(tmp_path, mode):
    """Every distributed failure shape of the sharded format — a corrupt
    shard (truncated or bit-flipped), a missing shard, a manifest-absent
    partial commit, and a writer preempted before the commit rename — makes
    `run_resilient` skip the damaged newest generation and recover
    bit-exactly from the previous one."""
    _grid()
    step_fn = _make_step()
    ref = _clean_run(step_fn, _init_state(), 20)

    igg.run_resilient(step_fn, _init_state(), 10, watch_every=5,
                      checkpoint_dir=tmp_path, checkpoint_every=5, ring=3)
    newest = igg.latest_checkpoint(tmp_path)
    assert igg.checkpoint.checkpoint_step(newest) == 10
    igg.chaos.corrupt_checkpoint(newest, mode)
    assert igg.latest_checkpoint(tmp_path) != newest

    res = igg.run_resilient(step_fn, _init_state(), 20, watch_every=5,
                            checkpoint_dir=tmp_path, checkpoint_every=5,
                            ring=3, resume=True)
    assert res.events[0].kind == "resume" and res.events[0].step == 5
    assert res.steps_done == 20
    np.testing.assert_array_equal(np.asarray(res.state["T"]), ref)


def test_async_checkpoints_commit_in_background(tmp_path):
    """The default ring (sharded + async): cadence generations are written
    by the background writer (events carry `background: True`), drained at
    end of run, and the newest one holds the final state bit-exactly."""
    _grid()
    step_fn = _make_step()
    res = igg.run_resilient(step_fn, _init_state(), 20, watch_every=5,
                            checkpoint_dir=tmp_path, checkpoint_every=5,
                            ring=3)
    cks = [e for e in res.events if e.kind == "checkpoint"]
    assert any(e.detail.get("background") for e in cks)       # cadence gens
    assert not cks[0].detail.get("background")                # entry gen sync
    assert not any(e.kind == "checkpoint_failed" for e in res.events)
    newest = igg.latest_checkpoint(tmp_path, check_finite=True)
    assert igg.checkpoint.checkpoint_step(newest) == 20
    out = igg.load_checkpoint(newest)
    np.testing.assert_array_equal(np.asarray(out["T"]),
                                  np.asarray(res.state["T"]))


def test_sync_and_flat_checkpoint_modes(tmp_path):
    """`async_checkpoint=False` writes every generation synchronously;
    `sharded=False` keeps the legacy flat `.npz` ring."""
    _grid()
    step_fn = _make_step()
    res = igg.run_resilient(step_fn, _init_state(), 10, watch_every=5,
                            checkpoint_dir=tmp_path / "sync",
                            checkpoint_every=5, async_checkpoint=False)
    assert not any(e.detail.get("background") for e in res.events
                   if e.kind == "checkpoint")
    assert igg.latest_checkpoint(tmp_path / "sync").is_dir()

    res = igg.run_resilient(step_fn, _init_state(), 10, watch_every=5,
                            checkpoint_dir=tmp_path / "flat",
                            checkpoint_every=5, sharded=False)
    newest = igg.latest_checkpoint(tmp_path / "flat")
    assert newest.name == "ckpt_000000010.npz" and newest.is_file()
    np.testing.assert_array_equal(
        np.asarray(igg.load_checkpoint(newest)["T"]),
        np.asarray(res.state["T"]))


def test_failed_background_write_degrades_ring_not_run(tmp_path, monkeypatch):
    """One background write failing (disk full, lost host) costs one ring
    generation and emits 'checkpoint_failed'; the run itself completes and
    the other generations commit."""
    from igg import checkpoint as ckpt

    _grid()
    step_fn = _make_step()
    real = ckpt.save_checkpoint_sharded
    calls = {"n": 0}

    def flaky(path, /, **fields):
        calls["n"] += 1
        if calls["n"] == 2:                  # first CADENCE write (entry
            raise OSError("disk full")       # generation is call #1, sync)
        return real(path, **fields)

    monkeypatch.setattr(ckpt, "save_checkpoint_sharded", flaky)
    res = igg.run_resilient(step_fn, _init_state(), 20, watch_every=5,
                            checkpoint_dir=tmp_path, checkpoint_every=5,
                            ring=10)
    fails = [e for e in res.events if e.kind == "checkpoint_failed"]
    assert len(fails) == 1 and "disk full" in fails[0].detail["error"]
    assert fails[0].step == 5      # the LOST generation's step, not the
    assert res.steps_done == 20    # step the failure was collected at
    from igg.checkpoint import list_generations
    steps = [s for s, _ in list_generations(tmp_path)]
    assert 5 not in steps                    # the lost generation
    assert {0, 10, 15, 20} <= set(steps)     # the rest committed


def test_elastic_resume_onto_different_topology(tmp_path):
    """A preempted run's sharded generation, written on the (2,2,2)
    8-device mesh, resumes on a (1,2,4) decomposition via
    `run_resilient(resume=True)` — re-tiled restore, then the remaining
    steps — and finishes bit-identical to an uninterrupted (2,2,2) run."""
    _grid()                                   # (2,2,2), periodic all
    step_fn = _make_step()
    state0 = _init_state()
    ref = np.asarray(igg.gather_interior(
        _clean_run_state(step_fn, dict(state0), 20)["T"]))

    plan = igg.chaos.ChaosPlan(preempt_at=10)
    res = igg.run_resilient(step_fn, state0, 20, watch_every=5,
                            checkpoint_dir=tmp_path, checkpoint_every=5,
                            chaos=plan)
    assert res.preempted and res.steps_done == 10
    igg.finalize_global_grid()

    # Same global domain (periodic: 2*(6-2) = 8 per dim) on (1,2,4):
    # locals 8/n + 2.
    igg.init_global_grid(10, 6, 4, dimx=1, dimy=2, dimz=4,
                         periodx=1, periody=1, periodz=1, quiet=True)
    step_fn2 = _make_step()
    rng = np.random.default_rng(0)
    dummy = {"T": igg.from_local_blocks(
        lambda c, ls: rng.standard_normal(ls), (10, 6, 4))}
    res2 = igg.run_resilient(step_fn2, dummy, 20, watch_every=5,
                             checkpoint_dir=tmp_path, checkpoint_every=5,
                             resume=True)
    assert res2.events[0].kind == "resume" and res2.events[0].step == 10
    assert res2.steps_done == 20
    np.testing.assert_array_equal(
        np.asarray(igg.gather_interior(res2.state["T"])), ref)


def _clean_run_state(step_fn, state, n):
    for _ in range(n):
        state = step_fn(state)
    return state


# ---------------------------------------------------------------------------
# Satellites: distributed-init retry, stale tmp sweep
# ---------------------------------------------------------------------------

def test_dist_init_retry_succeeds_after_flakes(monkeypatch):
    """Coordinator-not-yet-up: the initializer fails N times then succeeds;
    the retry loop absorbs it."""
    import jax

    from igg import init as iinit

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 4:
            raise RuntimeError("UNAVAILABLE: connection refused")

    monkeypatch.setattr(jax.distributed, "initialize", flaky)
    monkeypatch.setenv("IGG_DIST_INIT_BACKOFF", "0.001")
    monkeypatch.setenv("IGG_DIST_INIT_TIMEOUT", "30")
    assert iinit._init_distributed_with_retry() == 4
    assert calls["n"] == 4


def test_dist_init_timeout_names_coordinator(monkeypatch):
    import jax

    from igg import init as iinit

    def always_down():
        raise RuntimeError("UNAVAILABLE: connection refused")

    monkeypatch.setattr(jax.distributed, "initialize", always_down)
    monkeypatch.setenv("IGG_DIST_INIT_BACKOFF", "0.001")
    monkeypatch.setenv("IGG_DIST_INIT_TIMEOUT", "0.01")
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.9.8.7:8476")
    with pytest.raises(igg.GridError, match="10.9.8.7:8476"):
        iinit._init_distributed_with_retry()


def test_dist_init_retry_wired_into_init_global_grid(monkeypatch):
    """init_global_grid(init_distributed=True) goes through the retry
    wrapper (monkeypatched flaky initializer; 8-CPU mesh continues)."""
    import jax

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 2:
            raise RuntimeError("UNAVAILABLE")

    monkeypatch.setattr(jax.distributed, "initialize", flaky)
    monkeypatch.setenv("IGG_DIST_INIT_BACKOFF", "0.001")
    igg.init_global_grid(6, 6, 6, init_distributed=True, quiet=True)
    assert calls["n"] == 2
    igg.finalize_global_grid()


def test_stale_tmp_swept_with_one_time_warning(tmp_path, monkeypatch):
    import warnings

    from igg import checkpoint as ckpt

    monkeypatch.setattr(ckpt, "_warned_stale_tmp", False)
    _grid()
    state = _init_state()

    def _aged(path):
        path.write_bytes(b"half-written garbage")
        old = os.path.getmtime(path) - ckpt._STALE_TMP_AGE_S - 60
        os.utime(path, (old, old))
        return path

    stale = _aged(tmp_path / "old.npz.tmp")
    fresh = tmp_path / "live.npz.tmp"        # a live concurrent writer's
    fresh.write_bytes(b"mid-write")          # file must be left alone
    with pytest.warns(UserWarning, match="stale .tmp"):
        igg.save_checkpoint(tmp_path / "a.npz", **state)
    assert not stale.exists()
    assert fresh.exists()
    assert (tmp_path / "a.npz").exists()
    # One-time: a second sweep is silent.
    _aged(tmp_path / "old2.npz.tmp")
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        igg.save_checkpoint(tmp_path / "b.npz", **state)
    assert not (tmp_path / "old2.npz.tmp").exists()


def test_stale_staging_directory_swept(tmp_path, monkeypatch):
    """The sweep extends to orphaned `*.tmp` generation DIRECTORIES (a
    sharded writer crashed mid-commit): same age guard, same one-time
    warning — and a `.tmp` directory that is NOT our staging shape is
    never deleted from a shared checkpoint dir."""
    from igg import checkpoint as ckpt

    monkeypatch.setattr(ckpt, "_warned_stale_tmp", False)
    _grid()
    state = _init_state()

    def _age(path):
        old = os.path.getmtime(path) - ckpt._STALE_TMP_AGE_S - 60
        os.utime(path, (old, old))
        return path

    # A crashed sharded writer's staging dir: shard files (one still under
    # its own .tmp name), manifest never sealed — aged past the guard.
    stale = tmp_path / "ckpt_000000007.tmp"
    stale.mkdir()
    (stale / "shard_00000.npz").write_bytes(b"partial shard")
    (stale / "shard_00001.npz.tmp").write_bytes(b"mid-write shard")
    (stale / "manifest.json.tmp").write_bytes(b"{")
    _age(stale)
    # A foreign .tmp directory (not our staging shape): old, but kept.
    foreign = tmp_path / "other_tool.tmp"
    foreign.mkdir()
    (foreign / "notes.txt").write_text("not igg's to delete")
    _age(foreign)
    # A YOUNG staging dir may belong to a live concurrent writer: kept.
    fresh = tmp_path / "ckpt_000000009.tmp"
    fresh.mkdir()
    (fresh / "shard_00002.npz").write_bytes(b"live")

    with pytest.warns(UserWarning, match="stale .tmp"):
        igg.save_checkpoint_sharded(tmp_path / "a", **state)
    assert not stale.exists()
    assert foreign.exists() and (foreign / "notes.txt").exists()
    assert fresh.exists()
    assert igg.verify_checkpoint(tmp_path / "a")
