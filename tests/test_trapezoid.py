"""Trapezoidal K-step chunking: the exchange/window machinery on a real
multi-device (N,1,1) mesh.

The chunk KERNEL is manual-DMA (TPU-only; equivalence pinned on hardware by
tests/test_mega_tpu.py::test_trapezoid_matches_per_step_kernel).  What runs
here is everything around it: the K-deep slab ppermute pair, the
exchange-fresh window construction (`_extend_dim`), and the shrinking-validity
argument — realized in pure XLA on the 8-device CPU mesh and compared
against K per-step [stencil + update_halo] applications.
"""

import numpy as np
import pytest

import igg
from igg.ops.diffusion_pallas import _u_rows


def _window_steps(Text, A_ext, K, scal):
    """K plain stencil steps on the extended window (every row interior in
    x; y/z self-wrap) — the XLA realization of the chunk kernel's
    per-step update."""
    from jax import lax

    def step(_, U):
        S1, S2 = U.shape[1], U.shape[2]
        U = U.at[1:-1, 1:-1, 1:-1].set(
            _u_rows(U[:-2], U[1:-1], U[2:], A_ext[1:-1], **scal))
        U = U.at[:, 0, 1:-1].set(U[:, S1 - 2, 1:-1])
        U = U.at[:, S1 - 1, 1:-1].set(U[:, 1, 1:-1])
        U = U.at[:, :, 0].set(U[:, :, S2 - 2])
        U = U.at[:, :, S2 - 1].set(U[:, :, 1])
        return U

    return lax.fori_loop(0, K, step, Text)


def test_window_chunk_matches_per_step_on_ring():
    from igg.ops.diffusion_trapezoid import _extend_dim

    igg.init_global_grid(12, 8, 8, dimx=8, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    grid = igg.get_global_grid()
    K = 4
    ol = 2
    scal = dict(rdx2=0.3, rdy2=0.25, rdz2=0.2)

    rng = np.random.default_rng(9)
    T0 = igg.from_local_blocks(
        lambda coords, ls: rng.standard_normal(ls) + 10.0 * coords[0],
        (12, 8, 8))
    A0 = igg.from_local_blocks(
        lambda coords, ls: 0.05 + 0.01 * rng.random(ls), (12, 8, 8))
    # exchange-fresh entry state (the trapezoid's documented requirement)
    T0, A0 = igg.update_halo(T0, A0)

    @igg.sharded
    def chunk(T, A):
        A_ext = _extend_dim(A, K, ol, grid, 0)
        Text = _extend_dim(T, K, ol, grid, 0)
        return _window_steps(Text, A_ext, K, scal)[K:K + T.shape[0]]

    @igg.sharded
    def per_step(T, A):
        from jax import lax

        def one(_, T):
            S1, S2 = T.shape[1], T.shape[2]
            T = T.at[1:-1, 1:-1, 1:-1].set(
                _u_rows(T[:-2], T[1:-1], T[2:], A[1:-1], **scal))
            # y/z self-wrap (single periodic device), then the x exchange
            T = T.at[:, 0, 1:-1].set(T[:, S1 - 2, 1:-1])
            T = T.at[:, S1 - 1, 1:-1].set(T[:, 1, 1:-1])
            T = T.at[:, :, 0].set(T[:, :, S2 - 2])
            T = T.at[:, :, S2 - 1].set(T[:, :, 1])
            return igg.update_halo_local(T)

        return lax.fori_loop(0, K, one, T)

    out = np.asarray(chunk(T0, A0))
    ref = np.asarray(per_step(T0, A0))
    np.testing.assert_allclose(out, ref, rtol=0, atol=1e-12)


def _window_steps_2d(Text, A_ext, K, scal):
    """K stencil steps on a doubly-extended window (x AND y extended; z
    self-wrap)."""
    from jax import lax

    def step(_, U):
        S2 = U.shape[2]
        U = U.at[1:-1, 1:-1, 1:-1].set(
            _u_rows(U[:-2], U[1:-1], U[2:], A_ext[1:-1], **scal))
        U = U.at[:, :, 0].set(U[:, :, S2 - 2])
        U = U.at[:, :, S2 - 1].set(U[:, :, 1])
        return U

    return lax.fori_loop(0, K, step, Text)


def test_window_chunk_matches_per_step_on_torus():
    """(N,M,1) mesh: x and y both extended (corners via the y-neighbor's
    own x extension); compared against per-step [stencil + update_halo]."""
    from igg.ops.diffusion_trapezoid import _dim_modes, _extend

    igg.init_global_grid(12, 12, 8, dimx=4, dimy=2, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    grid = igg.get_global_grid()
    assert _dim_modes(grid) == ("ext", "ext", "wrap")
    K = 4
    scal = dict(rdx2=0.3, rdy2=0.25, rdz2=0.2)

    rng = np.random.default_rng(13)
    T0 = igg.from_local_blocks(
        lambda coords, ls: rng.standard_normal(ls) + 10.0 * coords[0]
        + 100.0 * coords[1], (12, 12, 8))
    A0 = igg.from_local_blocks(
        lambda coords, ls: 0.05 + 0.01 * rng.random(ls), (12, 12, 8))
    T0, A0 = igg.update_halo(T0, A0)

    @igg.sharded
    def chunk(T, A):
        A_ext = _extend(A, K, grid, T.shape, ("ext", "ext", "wrap"))
        Text = _extend(T, K, grid, T.shape, ("ext", "ext", "wrap"))
        out = _window_steps_2d(Text, A_ext, K, scal)
        return out[K:K + T.shape[0], K:K + T.shape[1]]

    @igg.sharded
    def per_step(T, A):
        from jax import lax

        def one(_, T):
            S2 = T.shape[2]
            T = T.at[1:-1, 1:-1, 1:-1].set(
                _u_rows(T[:-2], T[1:-1], T[2:], A[1:-1], **scal))
            T = T.at[:, :, 0].set(T[:, :, S2 - 2])
            T = T.at[:, :, S2 - 1].set(T[:, :, 1])
            return igg.update_halo_local(T)

        return lax.fori_loop(0, K, one, T)

    out = np.asarray(chunk(T0, A0))
    ref = np.asarray(per_step(T0, A0))
    np.testing.assert_allclose(out, ref, rtol=0, atol=1e-12)


def _window_steps_3d(Text, A_ext, K, scal):
    """K stencil steps on a triply-extended window (x, y AND z extended —
    no wraps; the shoulder cells of every dim lose validity each step)."""
    from jax import lax

    def step(_, U):
        return U.at[1:-1, 1:-1, 1:-1].set(
            _u_rows(U[:-2], U[1:-1], U[2:], A_ext[1:-1], **scal))

    return lax.fori_loop(0, K, step, Text)


def test_window_chunk_matches_per_step_on_3d_torus():
    """VERDICT round-3 item 2: the (2,2,2) 3-D torus — x, y and z all
    extended (edges/corners via the later neighbors' earlier-dim
    extensions; z slabs transpose-carried on the wire) — against per-step
    [stencil + update_halo]."""
    from igg.ops.diffusion_trapezoid import _dim_modes, _extend

    igg.init_global_grid(12, 12, 12, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    grid = igg.get_global_grid()
    assert _dim_modes(grid) == ("ext", "ext", "ext")
    K = 4
    scal = dict(rdx2=0.3, rdy2=0.25, rdz2=0.2)

    rng = np.random.default_rng(17)
    T0 = igg.from_local_blocks(
        lambda coords, ls: rng.standard_normal(ls) + 10.0 * coords[0]
        + 100.0 * coords[1] + 1000.0 * coords[2], (12, 12, 12))
    A0 = igg.from_local_blocks(
        lambda coords, ls: 0.05 + 0.01 * rng.random(ls), (12, 12, 12))
    T0, A0 = igg.update_halo(T0, A0)

    @igg.sharded
    def chunk(T, A):
        A_ext = _extend(A, K, grid, T.shape, ("ext", "ext", "ext"))
        Text = _extend(T, K, grid, T.shape, ("ext", "ext", "ext"))
        out = _window_steps_3d(Text, A_ext, K, scal)
        return out[K:K + T.shape[0], K:K + T.shape[1], K:K + T.shape[2]]

    @igg.sharded
    def per_step(T, A):
        from jax import lax

        def one(_, T):
            T = T.at[1:-1, 1:-1, 1:-1].set(
                _u_rows(T[:-2], T[1:-1], T[2:], A[1:-1], **scal))
            return igg.update_halo_local(T)

        return lax.fori_loop(0, K, one, T)

    out = np.asarray(chunk(T0, A0))
    ref = np.asarray(per_step(T0, A0))
    np.testing.assert_allclose(out, ref, rtol=0, atol=1e-12)


def test_model_path_interpret_3d_torus():
    """fused_diffusion_steps routes a (2,2,2) fully-periodic CPU mesh
    through the trapezoid chunking (XLA window fallback in interpret mode)
    and must match the plain XLA multi-step path."""
    import igg
    from igg.models import diffusion3d as d3
    from igg.ops.diffusion_trapezoid import trapezoid_supported

    igg.init_global_grid(16, 16, 128, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    grid = igg.get_global_grid()
    params = d3.Params(lx=8.0, ly=8.0, lz=60.0)
    T, Cp = d3.init_fields(params, dtype=np.float32)
    n_inner = 9  # warm-up step + one K=8 chunk
    assert trapezoid_supported(grid, (16, 16, 128), 8, n_inner - 1,
                               np.float32)

    ref_step = d3.make_multi_step(n_inner, params, use_pallas=False,
                                  donate=False)
    pal_step = d3.make_multi_step(n_inner, params, use_pallas=True,
                                  pallas_interpret=True, donate=False, bx=8)
    ref = np.asarray(ref_step(T, Cp), np.float64)
    out = np.asarray(pal_step(T, Cp), np.float64)
    scale = max(abs(ref).max(), 1e-30)
    assert abs(out - ref).max() <= 4e-6 * scale


def test_model_path_interpret_n1k():
    """(N,1,K) mesh: y self-wrap layered on the z-extended buffer — the one
    mode combination the torus tests don't reach."""
    import igg
    from igg.models import diffusion3d as d3
    from igg.ops.diffusion_trapezoid import _dim_modes, trapezoid_supported

    igg.init_global_grid(16, 16, 128, dimx=4, dimy=1, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    grid = igg.get_global_grid()
    assert _dim_modes(grid) == ("ext", "wrap", "ext")
    params = d3.Params(lx=8.0, ly=8.0, lz=60.0)
    T, Cp = d3.init_fields(params, dtype=np.float32)
    n_inner = 9
    assert trapezoid_supported(grid, (16, 16, 128), 8, n_inner - 1,
                               np.float32)

    ref_step = d3.make_multi_step(n_inner, params, use_pallas=False,
                                  donate=False)
    pal_step = d3.make_multi_step(n_inner, params, use_pallas=True,
                                  pallas_interpret=True, donate=False, bx=8)
    ref = np.asarray(ref_step(T, Cp), np.float64)
    out = np.asarray(pal_step(T, Cp), np.float64)
    scale = max(abs(ref).max(), 1e-30)
    assert abs(out - ref).max() <= 4e-6 * scale


def test_model_path_interpret_ring():
    """fused_diffusion_steps routes an (8,1,1) periodic CPU mesh through
    the trapezoid chunking (XLA window fallback in interpret mode) and must
    match the plain XLA multi-step path."""
    import numpy as np

    import igg
    from igg.models import diffusion3d as d3
    from igg.ops.diffusion_trapezoid import trapezoid_supported

    igg.init_global_grid(16, 16, 128, dimx=8, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    grid = igg.get_global_grid()
    params = d3.Params(lx=8.0, ly=8.0, lz=60.0)
    T, Cp = d3.init_fields(params, dtype=np.float32)
    n_inner = 9  # warm-up step + one K=8 chunk
    assert trapezoid_supported(grid, (16, 16, 128), 8, n_inner - 1,
                               np.float32)

    ref_step = d3.make_multi_step(n_inner, params, use_pallas=False,
                                  donate=False)
    # bx=8 so the chunk gate (n_inner-1 >= K=bx) holds: one 8-step chunk
    # through _window_steps_xla + the warm-up per-step.
    pal_step = d3.make_multi_step(n_inner, params, use_pallas=True,
                                  pallas_interpret=True, donate=False, bx=8)
    ref = np.asarray(ref_step(T, Cp), np.float64)
    out = np.asarray(pal_step(T, Cp), np.float64)
    scale = max(abs(ref).max(), 1e-30)
    assert abs(out - ref).max() <= 4e-6 * scale


def _chunk_vs_per_step_open(mesh, periods, K=8, shape=(16, 16, 128)):
    """Shared driver: one K-chunk of the open-boundary window realization
    (`fused_diffusion_trapezoid_steps(interpret=True)`) against K per-step
    [stencil + update_halo] applications, from an exchange-fresh state."""
    from jax import lax

    from igg.ops.diffusion_trapezoid import (_dim_modes,
                                             fused_diffusion_trapezoid_steps,
                                             trapezoid_supported)

    igg.init_global_grid(shape[0], shape[1], shape[2],
                         dimx=mesh[0], dimy=mesh[1], dimz=mesh[2],
                         periodx=periods[0], periody=periods[1],
                         periodz=periods[2], quiet=True)
    grid = igg.get_global_grid()
    scal = dict(rdx2=0.3, rdy2=0.25, rdz2=0.2)
    # allow_open=True is what the compiled dispatcher passes (round 6);
    # the conservative default still rejects open dims for direct callers.
    assert trapezoid_supported(grid, shape, K, K, np.float32,
                               allow_open=True)
    assert not trapezoid_supported(grid, shape, K, K, np.float32)

    rng = np.random.default_rng(29)
    T0 = igg.from_local_blocks(
        lambda coords, ls: rng.standard_normal(ls) + 10.0 * coords[0]
        + 100.0 * coords[1] + 1000.0 * coords[2], shape)
    A0 = igg.from_local_blocks(
        lambda coords, ls: 0.05 + 0.01 * rng.random(ls), shape)
    T0, A0 = igg.update_halo(T0, A0)   # exchange-fresh chunk entry

    @igg.sharded
    def chunk(T, A):
        out, done = fused_diffusion_trapezoid_steps(
            T, A, n_inner=K, bx=K, grid=grid, **scal, interpret=True)
        return out

    @igg.sharded
    def per_step(T, A):
        def one(_, T):
            T = T.at[1:-1, 1:-1, 1:-1].set(
                _u_rows(T[:-2], T[1:-1], T[2:], A[1:-1], **scal))
            return igg.update_halo_local(T)

        return lax.fori_loop(0, K, one, T)

    out = np.asarray(chunk(T0, A0))
    ref = np.asarray(per_step(T0, A0))
    np.testing.assert_allclose(out, ref, rtol=0, atol=1e-12)
    igg.finalize_global_grid()
    return _dim_modes(grid)


def test_model_path_interpret_open_mesh():
    """The compiled dispatcher (round 6) admits OPEN meshes to the chunk
    tier: `fused_diffusion_steps` must route an open (8,1,1) CPU mesh —
    the reference's default boundary condition — through the trapezoid
    chunking (XLA window fallback in interpret mode) and match the plain
    XLA multi-step path."""
    import igg
    from igg.models import diffusion3d as d3
    from igg.ops.diffusion_trapezoid import _dim_modes, trapezoid_supported

    igg.init_global_grid(16, 16, 128, dimx=8, dimy=1, dimz=1,
                         periodx=0, periody=0, periodz=0, quiet=True)
    grid = igg.get_global_grid()
    assert _dim_modes(grid) == ("oext", "frozen", "frozen")
    params = d3.Params(lx=8.0, ly=8.0, lz=60.0)
    T, Cp = d3.init_fields(params, dtype=np.float32)
    n_inner = 9  # warm-up step + one K=8 chunk
    assert trapezoid_supported(grid, (16, 16, 128), 8, n_inner - 1,
                               np.float32, allow_open=True)

    ref_step = d3.make_multi_step(n_inner, params, use_pallas=False,
                                  donate=False)
    pal_step = d3.make_multi_step(n_inner, params, use_pallas=True,
                                  pallas_interpret=True, donate=False, bx=8)
    ref = np.asarray(ref_step(T, Cp), np.float64)
    out = np.asarray(pal_step(T, Cp), np.float64)
    scale = max(abs(ref).max(), 1e-30)
    assert abs(out - ref).max() <= 4e-6 * scale


def test_open_x_window_chunk():
    """Open x over 8 devices, y/z open single (frozen edges): the 'oext'
    freeze masks must reproduce the per-step no-write halo semantics
    (`/root/reference/test/test_update_halo.jl:727-732`) exactly."""
    modes = _chunk_vs_per_step_open((8, 1, 1), (0, 0, 0))
    assert modes == ("oext", "frozen", "frozen")


def test_open_xz_window_chunk():
    """Mixed torus: open x and z over a (2,2,2) mesh with periodic
    extended y — open-edge freezing layered under later-dim extensions
    (corner values ride the y-neighbors' own frozen x rows)."""
    modes = _chunk_vs_per_step_open((2, 2, 2), (0, 1, 0))
    assert modes == ("oext", "ext", "oext")


def test_open_y_window_chunk():
    """Periodic x/z rings around an open y split: 'oext' between two
    periodic extensions."""
    modes = _chunk_vs_per_step_open((2, 2, 2), (1, 0, 1))
    assert modes == ("ext", "oext", "ext")
