"""Trapezoidal K-step chunking: the exchange/window machinery on a real
multi-device (N,1,1) mesh.

The chunk KERNEL is manual-DMA (TPU-only; equivalence pinned on hardware by
tests/test_mega_tpu.py::test_trapezoid_matches_per_step_kernel).  What runs
here is everything around it: the K-deep slab ppermute pair, the
exchange-fresh window construction (`_extend_x`), and the shrinking-validity
argument — realized in pure XLA on the 8-device CPU mesh and compared
against K per-step [stencil + update_halo] applications.
"""

import numpy as np
import pytest

import igg
from igg.ops.diffusion_pallas import _u_rows


def _window_steps(Text, A_ext, K, scal):
    """K plain stencil steps on the extended window (every row interior in
    x; y/z self-wrap) — the XLA realization of the chunk kernel's
    per-step update."""
    from jax import lax

    def step(_, U):
        S1, S2 = U.shape[1], U.shape[2]
        U = U.at[1:-1, 1:-1, 1:-1].set(
            _u_rows(U[:-2], U[1:-1], U[2:], A_ext[1:-1], **scal))
        U = U.at[:, 0, 1:-1].set(U[:, S1 - 2, 1:-1])
        U = U.at[:, S1 - 1, 1:-1].set(U[:, 1, 1:-1])
        U = U.at[:, :, 0].set(U[:, :, S2 - 2])
        U = U.at[:, :, S2 - 1].set(U[:, :, 1])
        return U

    return lax.fori_loop(0, K, step, Text)


def test_window_chunk_matches_per_step_on_ring():
    from igg.ops.diffusion_trapezoid import _extend_x

    igg.init_global_grid(12, 8, 8, dimx=8, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    grid = igg.get_global_grid()
    K = 4
    ol = 2
    scal = dict(rdx2=0.3, rdy2=0.25, rdz2=0.2)

    rng = np.random.default_rng(9)
    T0 = igg.from_local_blocks(
        lambda coords, ls: rng.standard_normal(ls) + 10.0 * coords[0],
        (12, 8, 8))
    A0 = igg.from_local_blocks(
        lambda coords, ls: 0.05 + 0.01 * rng.random(ls), (12, 8, 8))
    # exchange-fresh entry state (the trapezoid's documented requirement)
    T0, A0 = igg.update_halo(T0, A0)

    @igg.sharded
    def chunk(T, A):
        A_ext = _extend_x(A, K, ol, grid)
        Text = _extend_x(T, K, ol, grid)
        return _window_steps(Text, A_ext, K, scal)[K:K + T.shape[0]]

    @igg.sharded
    def per_step(T, A):
        from jax import lax

        def one(_, T):
            S1, S2 = T.shape[1], T.shape[2]
            T = T.at[1:-1, 1:-1, 1:-1].set(
                _u_rows(T[:-2], T[1:-1], T[2:], A[1:-1], **scal))
            # y/z self-wrap (single periodic device), then the x exchange
            T = T.at[:, 0, 1:-1].set(T[:, S1 - 2, 1:-1])
            T = T.at[:, S1 - 1, 1:-1].set(T[:, 1, 1:-1])
            T = T.at[:, :, 0].set(T[:, :, S2 - 2])
            T = T.at[:, :, S2 - 1].set(T[:, :, 1])
            return igg.update_halo_local(T)

        return lax.fori_loop(0, K, one, T)

    out = np.asarray(chunk(T0, A0))
    ref = np.asarray(per_step(T0, A0))
    np.testing.assert_allclose(out, ref, rtol=0, atol=1e-12)
