"""Checkpoint / resume (igg/checkpoint.py) — a TPU-native extension (the
reference has no checkpoint facility; SURVEY §5)."""

import numpy as np
import pytest

import igg


def _mkfields():
    rng = np.random.default_rng(21)
    T = igg.from_local_blocks(
        lambda coords, ls: rng.standard_normal(ls) + 7.0 * coords[0],
        (6, 6, 6))
    Vx = igg.from_local_blocks(
        lambda coords, ls: rng.standard_normal(ls), (7, 6, 6))  # staggered
    return T, Vx


def test_roundtrip(tmp_path):
    igg.init_global_grid(6, 6, 6, periodx=1, quiet=True)
    T, Vx = _mkfields()
    igg.save_checkpoint(tmp_path / "ck.npz", T=T, Vx=Vx)
    out = igg.load_checkpoint(tmp_path / "ck.npz")
    assert set(out) == {"T", "Vx"}
    np.testing.assert_array_equal(np.asarray(out["T"]), np.asarray(T))
    np.testing.assert_array_equal(np.asarray(out["Vx"]), np.asarray(Vx))
    # restored arrays are live sharded fields: a halo update must work
    igg.update_halo(out["T"])
    igg.finalize_global_grid()


def test_resume_continues_identically(tmp_path):
    """A solver resumed from a checkpoint must continue bit-for-bit."""
    import jax

    from igg.ops import interior_add

    igg.init_global_grid(6, 6, 6, periodx=1, periody=1, periodz=1,
                         quiet=True)

    @igg.sharded
    def step(T):
        lap = (T[:-2, 1:-1, 1:-1] + T[2:, 1:-1, 1:-1]
               + T[1:-1, :-2, 1:-1] + T[1:-1, 2:, 1:-1]
               + T[1:-1, 1:-1, :-2] + T[1:-1, 1:-1, 2:]
               - 6.0 * T[1:-1, 1:-1, 1:-1])
        return igg.update_halo_local(interior_add(T, 0.1 * lap))

    T, _ = _mkfields()
    T = igg.update_halo(T)
    for _ in range(3):
        T = step(T)
    igg.save_checkpoint(tmp_path / "mid.npz", T=T)
    for _ in range(3):
        T = step(T)
    ref = np.asarray(T)

    T2 = igg.load_checkpoint(tmp_path / "mid.npz")["T"]
    for _ in range(3):
        T2 = step(T2)
    np.testing.assert_array_equal(np.asarray(T2), ref)
    igg.finalize_global_grid()


def test_geometry_mismatch_rejected(tmp_path):
    igg.init_global_grid(6, 6, 6, quiet=True)
    T, _ = _mkfields()
    igg.save_checkpoint(tmp_path / "ck.npz", T=T)
    igg.finalize_global_grid()

    igg.init_global_grid(6, 6, 6, periodx=1, quiet=True)  # different periods
    with pytest.raises(igg.GridError, match="geometry mismatch"):
        igg.load_checkpoint(tmp_path / "ck.npz")
    igg.finalize_global_grid()

    igg.init_global_grid(8, 6, 6, quiet=True)  # different local size
    with pytest.raises(igg.GridError, match="geometry mismatch"):
        igg.load_checkpoint(tmp_path / "ck.npz")
    igg.finalize_global_grid()


def test_misuse(tmp_path):
    igg.init_global_grid(6, 6, 6, quiet=True)
    with pytest.raises(igg.GridError, match="no fields"):
        igg.save_checkpoint(tmp_path / "ck.npz")
    T, _ = _mkfields()
    with pytest.raises(igg.GridError, match="reserved"):
        igg.save_checkpoint(tmp_path / "ck.npz", **{"__igg_meta__": T})
    igg.finalize_global_grid()


def test_bfloat16_and_path_and_names(tmp_path):
    import jax.numpy as jnp

    igg.init_global_grid(6, 6, 6, periodx=1, quiet=True)
    T = (igg.zeros((6, 6, 6), dtype=jnp.bfloat16)
         + jnp.asarray(3.5, jnp.bfloat16))
    # suffix-less path must round-trip to the exact path given, and a field
    # named "file" must not collide with np.savez internals
    igg.save_checkpoint(tmp_path / "ck", T=T, file=T)
    out = igg.load_checkpoint(tmp_path / "ck")
    assert out["T"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(out["T"], np.float32), np.asarray(T, np.float32))
    np.testing.assert_array_equal(
        np.asarray(out["file"], np.float32), np.asarray(T, np.float32))
    igg.finalize_global_grid()
