"""Checkpoint / resume (igg/checkpoint.py) — a TPU-native extension (the
reference has no checkpoint facility; SURVEY §5)."""

import numpy as np
import pytest

import igg


def _mkfields():
    rng = np.random.default_rng(21)
    T = igg.from_local_blocks(
        lambda coords, ls: rng.standard_normal(ls) + 7.0 * coords[0],
        (6, 6, 6))
    Vx = igg.from_local_blocks(
        lambda coords, ls: rng.standard_normal(ls), (7, 6, 6))  # staggered
    return T, Vx


def test_roundtrip(tmp_path):
    igg.init_global_grid(6, 6, 6, periodx=1, quiet=True)
    T, Vx = _mkfields()
    igg.save_checkpoint(tmp_path / "ck.npz", T=T, Vx=Vx)
    out = igg.load_checkpoint(tmp_path / "ck.npz")
    assert set(out) == {"T", "Vx"}
    np.testing.assert_array_equal(np.asarray(out["T"]), np.asarray(T))
    np.testing.assert_array_equal(np.asarray(out["Vx"]), np.asarray(Vx))
    # restored arrays are live sharded fields: a halo update must work
    igg.update_halo(out["T"])
    igg.finalize_global_grid()


def test_resume_continues_identically(tmp_path):
    """A solver resumed from a checkpoint must continue bit-for-bit."""
    import jax

    from igg.ops import interior_add

    igg.init_global_grid(6, 6, 6, periodx=1, periody=1, periodz=1,
                         quiet=True)

    @igg.sharded
    def step(T):
        lap = (T[:-2, 1:-1, 1:-1] + T[2:, 1:-1, 1:-1]
               + T[1:-1, :-2, 1:-1] + T[1:-1, 2:, 1:-1]
               + T[1:-1, 1:-1, :-2] + T[1:-1, 1:-1, 2:]
               - 6.0 * T[1:-1, 1:-1, 1:-1])
        return igg.update_halo_local(interior_add(T, 0.1 * lap))

    T, _ = _mkfields()
    T = igg.update_halo(T)
    for _ in range(3):
        T = step(T)
    igg.save_checkpoint(tmp_path / "mid.npz", T=T)
    for _ in range(3):
        T = step(T)
    ref = np.asarray(T)

    T2 = igg.load_checkpoint(tmp_path / "mid.npz")["T"]
    for _ in range(3):
        T2 = step(T2)
    np.testing.assert_array_equal(np.asarray(T2), ref)
    igg.finalize_global_grid()


def test_geometry_mismatch_rejected(tmp_path):
    igg.init_global_grid(6, 6, 6, quiet=True)
    T, _ = _mkfields()
    igg.save_checkpoint(tmp_path / "ck.npz", T=T)
    igg.finalize_global_grid()

    igg.init_global_grid(6, 6, 6, periodx=1, quiet=True)  # different periods
    with pytest.raises(igg.GridError, match="geometry mismatch"):
        igg.load_checkpoint(tmp_path / "ck.npz")
    igg.finalize_global_grid()

    igg.init_global_grid(8, 6, 6, quiet=True)  # different local size
    with pytest.raises(igg.GridError, match="geometry mismatch"):
        igg.load_checkpoint(tmp_path / "ck.npz")
    igg.finalize_global_grid()


class TestRedistribute:
    """load_checkpoint(redistribute=True): save on one decomposition,
    restore onto another with bit-identical interiors (VERDICT r3 item 8)."""

    @staticmethod
    def _save(tmp_path, periods):
        from helpers import encoded_field

        igg.init_global_grid(6, 6, 6, quiet=True, **periods)   # (2,2,2)
        T = igg.update_halo(encoded_field((6, 6, 6)))
        Vx = igg.update_halo(encoded_field((7, 6, 6)))         # staggered
        igg.save_checkpoint(tmp_path / "ck.npz", T=T, Vx=Vx)
        want = {k: np.asarray(igg.gather_interior(v))
                for k, v in (("T", T), ("Vx", Vx))}
        igg.finalize_global_grid()
        return want

    @pytest.mark.parametrize("periods", [
        dict(periodx=1, periody=1, periodz=1), dict(periody=1), {}])
    @pytest.mark.parametrize("target", [
        dict(dimx=1, dimy=1, dimz=1), dict(dimx=4, dimy=2, dimz=1)])
    def test_bit_identical_interiors(self, tmp_path, periods, target):
        want = self._save(tmp_path, periods)
        # Solve the target local sizes so the global domain matches the
        # (2,2,2) source (base 6, ol 2: interior per dim = 2*4 + 2*open):
        # n*(s-2) + 2*open == size  ->  s = (size - 2*open)/n + 2.
        local = []
        for d, (dkey, pkey) in enumerate((("dimx", "periodx"),
                                          ("dimy", "periody"),
                                          ("dimz", "periodz"))):
            open_b = not periods.get(pkey, 0)
            size = 2 * 4 + (2 if open_b else 0)    # source global interior
            n = target.get(dkey, 1)
            local.append((size - (2 if open_b else 0)) // n + 2)
        igg.init_global_grid(*local, quiet=True, **periods, **target)
        out = igg.load_checkpoint(tmp_path / "ck.npz", redistribute=True)
        for name in ("T", "Vx"):
            got = np.asarray(igg.gather_interior(out[name]))
            np.testing.assert_array_equal(got, want[name])
        # restored fields are live: a halo update must run
        igg.update_halo(out["T"])
        igg.finalize_global_grid()

    def test_periodicity_change_rejected(self, tmp_path):
        self._save(tmp_path, dict(periodx=1))
        igg.init_global_grid(10, 6, 6, dimx=1, dimy=1, dimz=1, quiet=True)
        with pytest.raises(igg.GridError, match="periodicity"):
            igg.load_checkpoint(tmp_path / "ck.npz", redistribute=True)
        igg.finalize_global_grid()

    def test_wrong_domain_rejected(self, tmp_path):
        self._save(tmp_path, dict(periodx=1))
        igg.init_global_grid(7, 7, 7, dimx=1, dimy=1, dimz=1, periodx=1,
                             quiet=True)
        with pytest.raises(igg.GridError, match="physical domain"):
            igg.load_checkpoint(tmp_path / "ck.npz", redistribute=True)
        igg.finalize_global_grid()


def test_misuse(tmp_path):
    igg.init_global_grid(6, 6, 6, quiet=True)
    with pytest.raises(igg.GridError, match="no fields"):
        igg.save_checkpoint(tmp_path / "ck.npz")
    T, _ = _mkfields()
    with pytest.raises(igg.GridError, match="reserved"):
        igg.save_checkpoint(tmp_path / "ck.npz", **{"__igg_meta__": T})
    igg.finalize_global_grid()


def test_bfloat16_and_path_and_names(tmp_path):
    import jax.numpy as jnp

    igg.init_global_grid(6, 6, 6, periodx=1, quiet=True)
    T = (igg.zeros((6, 6, 6), dtype=jnp.bfloat16)
         + jnp.asarray(3.5, jnp.bfloat16))
    # suffix-less path must round-trip to the exact path given, and a field
    # named "file" must not collide with np.savez internals
    igg.save_checkpoint(tmp_path / "ck", T=T, file=T)
    out = igg.load_checkpoint(tmp_path / "ck")
    assert out["T"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(out["T"], np.float32), np.asarray(T, np.float32))
    np.testing.assert_array_equal(
        np.asarray(out["file"], np.float32), np.asarray(T, np.float32))
    igg.finalize_global_grid()


class TestSharded:
    """The sharded generation format (igg-sharded-v1): O(local) save, the
    manifest-written-last commit, and the ELASTIC restore path — a
    generation written on the (2,2,2) 8-device mesh restores bit-exactly
    (interiors AND halos, periodic and open dims) onto (1,2,4) and onto a
    4-device mesh, without any process materializing the global array."""

    @staticmethod
    def _save(tmp_path, periods):
        from helpers import encoded_field

        igg.init_global_grid(6, 6, 6, quiet=True, **periods)   # (2,2,2)
        T = igg.update_halo(encoded_field((6, 6, 6)))
        Vx = igg.update_halo(encoded_field((7, 6, 6)))         # staggered
        igg.save_checkpoint_sharded(tmp_path / "gen", T=T, Vx=Vx)
        want = {
            "interior": {k: np.asarray(igg.gather_interior(v))
                         for k, v in (("T", T), ("Vx", Vx))},
            "stacked": {k: np.asarray(v) for k, v in (("T", T), ("Vx", Vx))},
        }
        igg.finalize_global_grid()
        return want

    @staticmethod
    def _target_locals(target_dims):
        """Local sizes on `target_dims` matching the (2,2,2)/local-6 source
        global domain: interior per dim = 2*(6-2) + 2*open and
        n*(s-2) + 2*open == that, so s = 8/n + 2 independent of openness."""
        return [2 * 4 // n + 2 for n in target_dims]

    def test_roundtrip_same_geometry(self, tmp_path):
        want = self._save(tmp_path, dict(periodx=1))
        igg.init_global_grid(6, 6, 6, periodx=1, quiet=True)
        assert igg.verify_checkpoint(tmp_path / "gen", check_finite=True)
        out = igg.load_checkpoint(tmp_path / "gen")
        for name in ("T", "Vx"):
            np.testing.assert_array_equal(np.asarray(out[name]),
                                          want["stacked"][name])
        igg.update_halo(out["T"])    # restored fields are live

    @pytest.mark.parametrize("periods", [
        dict(periodx=1, periody=1, periodz=1), dict(periodx=1), {}])
    @pytest.mark.parametrize("target", [(1, 2, 4), (4, 2, 1)])
    def test_elastic_restore_bit_exact_including_halos(
            self, tmp_path, periods, target):
        from helpers import encoded_field

        want = self._save(tmp_path, periods)
        local = self._target_locals(target)
        igg.init_global_grid(*local, dimx=target[0], dimy=target[1],
                             dimz=target[2], quiet=True, **periods)
        out = igg.load_checkpoint(tmp_path / "gen", redistribute=True)
        for name, ls in (("T", tuple(local)),
                         ("Vx", (local[0] + 1,) + tuple(local[1:]))):
            got_i = np.asarray(igg.gather_interior(out[name]))
            np.testing.assert_array_equal(got_i, want["interior"][name])
            # The FULL stacked array — halo cells included — must equal the
            # coordinate-encoded field built natively on the target grid:
            # interiors bit-exact, periodic-wrap halos reconstructed, and
            # open-boundary outer planes carrying the (user-owned) encoded
            # values the source wrote.
            exp = np.asarray(igg.update_halo(encoded_field(ls)))
            np.testing.assert_array_equal(np.asarray(out[name]), exp)

    def test_elastic_restore_onto_four_device_mesh(self, tmp_path):
        """Device-count elasticity: a generation from the 8-device (2,2,2)
        mesh restores onto a 4-device (2,2,1) mesh of the same host."""
        import jax

        from helpers import encoded_field

        want = self._save(tmp_path, dict(periodx=1))
        igg.init_global_grid(6, 6, 10, dimx=2, dimy=2, dimz=1, periodx=1,
                             quiet=True, devices=jax.devices()[:4])
        out = igg.load_checkpoint(tmp_path / "gen", redistribute=True)
        np.testing.assert_array_equal(
            np.asarray(igg.gather_interior(out["T"])), want["interior"]["T"])
        exp = np.asarray(igg.update_halo(encoded_field((6, 6, 10))))
        np.testing.assert_array_equal(np.asarray(out["T"]), exp)

    def test_no_process_materializes_the_global_array(self, tmp_path,
                                                      monkeypatch):
        """Sentinel proof of the O(local) contract: the sharded save and
        BOTH restore paths (1:1 and elastic) never touch the global-array
        assembly (`gather._fetch_global`) or `process_allgather`."""
        import importlib

        from jax.experimental import multihost_utils

        gather_mod = importlib.import_module("igg.gather")

        def boom(*a, **k):
            raise AssertionError("global-array path used by the sharded "
                                 "checkpoint layer")

        self._save(tmp_path, dict(periodx=1))
        monkeypatch.setattr(gather_mod, "_fetch_global", boom)
        monkeypatch.setattr(multihost_utils, "process_allgather", boom)

        igg.init_global_grid(6, 6, 6, periodx=1, quiet=True)
        state = igg.load_checkpoint(tmp_path / "gen")           # 1:1
        igg.save_checkpoint_sharded(tmp_path / "gen2", **state)  # save
        assert igg.verify_checkpoint(tmp_path / "gen2")
        igg.finalize_global_grid()

        igg.init_global_grid(10, 6, 4, dimx=1, dimy=2, dimz=4, periodx=1,
                             quiet=True)
        igg.load_checkpoint(tmp_path / "gen", redistribute=True)  # elastic

    def test_uncommitted_generation_is_invalid(self, tmp_path):
        """No manifest == no commit: the generation reads as invalid and
        latest_checkpoint skips it, exactly like a truncated flat file."""
        self._save(tmp_path, {})
        igg.init_global_grid(6, 6, 6, quiet=True)
        (tmp_path / "gen" / "manifest.json").unlink()
        assert not igg.verify_checkpoint(tmp_path / "gen")
        with pytest.raises(igg.GridError, match="uncommitted"):
            igg.load_checkpoint(tmp_path / "gen")

    def test_staging_dir_is_not_a_generation(self, tmp_path):
        """A `.tmp`-staged directory (writer died before the commit rename)
        is invisible to the generation scan."""
        from igg.checkpoint import list_generations

        self._save(tmp_path, {})
        igg.init_global_grid(6, 6, 6, quiet=True)
        gen = tmp_path / "ckpt_000000005"
        (tmp_path / "gen").rename(gen)
        assert [s for s, _ in list_generations(tmp_path)] == [5]
        igg.chaos.corrupt_checkpoint(gen, "preempt_mid_write")
        assert list_generations(tmp_path) == []
        assert igg.latest_checkpoint(tmp_path) is None

    def test_corrupt_and_missing_shards_detected(self, tmp_path):
        self._save(tmp_path, {})
        igg.init_global_grid(6, 6, 6, quiet=True)
        ok = tmp_path / "gen"
        assert igg.verify_checkpoint(ok, check_finite=True)

        import shutil
        for mode, match in (("bitflip", "CRC32 mismatch"),
                            ("truncate", "cannot read shard"),
                            ("missing_shard", "cannot read shard")):
            bad = tmp_path / f"bad_{mode}"
            shutil.copytree(ok, bad)
            igg.chaos.corrupt_checkpoint(bad, mode, shard=3)
            assert not igg.verify_checkpoint(bad)
            with pytest.raises(igg.GridError, match=match):
                igg.load_checkpoint(bad)

    def test_shard_swap_caught_by_summary_crc(self, tmp_path):
        """Two shards swapped on disk: each is self-consistent (its own
        CRCs pass), only the generation manifest's summary CRC ties shard
        files to the write that produced them."""
        import os

        self._save(tmp_path, {})
        igg.init_global_grid(6, 6, 6, quiet=True)
        gen = tmp_path / "gen"
        a, b = gen / "shard_00000.npz", gen / "shard_00007.npz"
        tmp = gen / "swap"
        os.replace(a, tmp), os.replace(b, a), os.replace(tmp, b)
        assert not igg.verify_checkpoint(gen)
        with pytest.raises(igg.GridError, match="summary CRC32"):
            igg.load_checkpoint(gen)

    def test_verify_distributed_single_process_equals_plain(self, tmp_path):
        self._save(tmp_path, {})
        igg.init_global_grid(6, 6, 6, quiet=True)
        assert igg.verify_checkpoint_distributed(tmp_path / "gen",
                                                 check_finite=True)
        igg.chaos.corrupt_checkpoint(tmp_path / "gen", "bitflip")
        assert not igg.verify_checkpoint_distributed(tmp_path / "gen")

    def test_misuse(self, tmp_path):
        igg.init_global_grid(6, 6, 6, quiet=True)
        T, _ = _mkfields()
        with pytest.raises(igg.GridError, match="no fields"):
            igg.save_checkpoint_sharded(tmp_path / "gen")
        with pytest.raises(igg.GridError, match="reserved"):
            igg.save_checkpoint_sharded(tmp_path / "gen",
                                        **{"__igg_meta__": T})
        with pytest.raises(igg.GridError, match="DIRECTORY"):
            igg.save_checkpoint_sharded(tmp_path / "gen.npz", T=T)
        with pytest.raises(igg.GridError, match="periodicity"):
            self._mismatched_periods(tmp_path, T)

    @staticmethod
    def _mismatched_periods(tmp_path, T):
        igg.save_checkpoint_sharded(tmp_path / "p0", T=T)
        igg.finalize_global_grid()
        igg.init_global_grid(10, 6, 6, dimx=1, dimy=1, dimz=1, periodx=1,
                             quiet=True)
        igg.load_checkpoint(tmp_path / "p0", redistribute=True)

    def test_bf16_and_rank4_sharded(self, tmp_path):
        """Extension dtypes (raw-byte encoded, dtype restored from the
        manifest) and rank-4 component-stacked fields round-trip through
        the sharded format, elastic restore included."""
        import jax.numpy as jnp

        from helpers import encoded_field

        igg.init_global_grid(6, 6, 6, periodx=1, quiet=True)   # (2,2,2)
        B = (igg.zeros((6, 6, 6), dtype=jnp.bfloat16)
             + jnp.asarray(2.5, jnp.bfloat16))
        U = igg.update_halo(encoded_field((6, 6, 6, 2)))
        igg.save_checkpoint_sharded(tmp_path / "gen", B=B, U=U)
        out = igg.load_checkpoint(tmp_path / "gen")
        assert out["B"].dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(out["B"], np.float32), np.asarray(B, np.float32))
        np.testing.assert_array_equal(np.asarray(out["U"]), np.asarray(U))
        want_U = np.asarray(igg.gather_interior(U))
        igg.finalize_global_grid()

        igg.init_global_grid(10, 6, 6, dimx=1, dimy=2, dimz=2, periodx=1,
                             quiet=True)
        out = igg.load_checkpoint(tmp_path / "gen", redistribute=True)
        assert out["B"].dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(igg.gather_interior(out["U"])), want_U)

    def test_generation_overwrite_is_atomic_replace(self, tmp_path):
        """Saving over an existing committed generation replaces it whole
        (the staged-rename pattern), never merges shard sets."""
        from helpers import encoded_field

        igg.init_global_grid(6, 6, 6, periodx=1, quiet=True)
        T = igg.update_halo(encoded_field((6, 6, 6)))
        igg.save_checkpoint_sharded(tmp_path / "gen", T=T, Extra=T)
        igg.save_checkpoint_sharded(tmp_path / "gen", T=T)
        out = igg.load_checkpoint(tmp_path / "gen")
        assert set(out) == {"T"}

    def test_attempt_handshake_ignores_dead_attempt_leftovers(self,
                                                              tmp_path):
        """The multi-controller commit handshake at the filesystem level:
        a peer entering a save while a DEAD attempt's staging dir (stale
        hello, ack, and token file) still sits at the staging name must
        never adopt the stale attempt — it returns only the token a live
        process 0 issues AFTER clearing the leftovers, even though the
        clear races the peer's polling."""
        import threading
        import time

        from igg.checkpoint import (_ACK, _HELLO, _ack_hellos,
                                    _peer_handshake)

        staging = tmp_path / "ckpt_000000005.tmp"
        staging.mkdir()
        # Dead attempt's leftovers: the peer's own stale hello (answered!)
        # plus another rank's — the worst case, an ack already matching a
        # hello at the peer's OWN rank from the dead run.
        (staging / _HELLO.format(1)).write_text("stalenonce")
        (staging / _ACK.format(1)).write_text("stalenonce\nstaletoken")
        (staging / _HELLO.format(2)).write_text("othernonce")
        (staging / "attempt.token").write_text("staletoken")

        got = {}

        def peer():
            got["token"] = _peer_handshake(staging, 1)

        t = threading.Thread(target=peer)
        t.start()
        time.sleep(0.2)       # let the peer observe the stale staging dir
        # Process 0 of the relaunch: clear the dead attempt, restage, and
        # answer hellos from the shard-wait poll loop.  The clear uses the
        # production helper: the live peer's re-hello can land DURING the
        # rmtree (a real race this test used to lose on loaded hosts).
        from igg.checkpoint import _rmtree_contended

        _rmtree_contended(staging)
        staging.mkdir()
        deadline = time.monotonic() + 10.0
        while t.is_alive() and time.monotonic() < deadline:
            _ack_hellos(staging, "freshtoken")
            time.sleep(0.02)
        t.join(timeout=10.0)
        assert not t.is_alive()
        assert got["token"] == "freshtoken"
        # The peer confirmed receipt (the third leg process 0 awaits
        # before sealing, so even shard-less peers finish the handshake).
        assert (staging / "done_00001").read_text() == (
            staging / "hello_00001").read_text()

    def test_same_step_flat_and_sharded_both_candidates(self, tmp_path):
        """A step can hold BOTH artifacts — a sharded directory and a stale
        flat file from a `sharded=False` run.  A corrupt one must not mask
        the valid one: latest_checkpoint tries every generation, not one
        per step."""
        from helpers import encoded_field

        igg.init_global_grid(6, 6, 6, quiet=True)
        T = igg.update_halo(encoded_field((6, 6, 6)))
        igg.save_checkpoint_sharded(tmp_path / "ckpt_000000007", T=T)
        igg.save_checkpoint(tmp_path / "ckpt_000000007.npz", T=T)
        igg.chaos.corrupt_checkpoint(tmp_path / "ckpt_000000007.npz",
                                     "truncate")
        found = igg.latest_checkpoint(tmp_path)
        assert found is not None and found.is_dir()   # the valid sibling

    def test_handshake_files_not_in_committed_generation(self, tmp_path):
        """Hello/ack handshake files are save-time scaffolding; a committed
        generation holds only shards and the manifest."""
        import re

        self._save(tmp_path, {})
        names = {p.name for p in (tmp_path / "gen").iterdir()}
        assert "manifest.json" in names
        assert all(n == "manifest.json" or re.fullmatch(r"shard_\d+\.npz", n)
                   for n in names)


def test_rank4_roundtrip_and_redistribute(tmp_path):
    """Rank-4 component-stacked fields checkpoint and redistribute like
    rank-3 ones (trailing dims unsharded)."""
    from helpers import encoded_field

    igg.init_global_grid(6, 6, 6, periodx=1, quiet=True)       # (2,2,2)
    U = igg.update_halo(encoded_field((6, 6, 6, 2)))
    igg.save_checkpoint(tmp_path / "r4.npz", U=U)
    out = igg.load_checkpoint(tmp_path / "r4.npz")["U"]
    np.testing.assert_array_equal(np.asarray(out), np.asarray(U))
    want = np.asarray(igg.gather_interior(U))
    igg.finalize_global_grid()

    igg.init_global_grid(10, 6, 6, dimx=1, dimy=2, dimz=2, periodx=1,
                         quiet=True)
    out = igg.load_checkpoint(tmp_path / "r4.npz", redistribute=True)["U"]
    np.testing.assert_array_equal(np.asarray(igg.gather_interior(out)), want)
    igg.finalize_global_grid()
