"""Checkpoint / resume (igg/checkpoint.py) — a TPU-native extension (the
reference has no checkpoint facility; SURVEY §5)."""

import numpy as np
import pytest

import igg


def _mkfields():
    rng = np.random.default_rng(21)
    T = igg.from_local_blocks(
        lambda coords, ls: rng.standard_normal(ls) + 7.0 * coords[0],
        (6, 6, 6))
    Vx = igg.from_local_blocks(
        lambda coords, ls: rng.standard_normal(ls), (7, 6, 6))  # staggered
    return T, Vx


def test_roundtrip(tmp_path):
    igg.init_global_grid(6, 6, 6, periodx=1, quiet=True)
    T, Vx = _mkfields()
    igg.save_checkpoint(tmp_path / "ck.npz", T=T, Vx=Vx)
    out = igg.load_checkpoint(tmp_path / "ck.npz")
    assert set(out) == {"T", "Vx"}
    np.testing.assert_array_equal(np.asarray(out["T"]), np.asarray(T))
    np.testing.assert_array_equal(np.asarray(out["Vx"]), np.asarray(Vx))
    # restored arrays are live sharded fields: a halo update must work
    igg.update_halo(out["T"])
    igg.finalize_global_grid()


def test_resume_continues_identically(tmp_path):
    """A solver resumed from a checkpoint must continue bit-for-bit."""
    import jax

    from igg.ops import interior_add

    igg.init_global_grid(6, 6, 6, periodx=1, periody=1, periodz=1,
                         quiet=True)

    @igg.sharded
    def step(T):
        lap = (T[:-2, 1:-1, 1:-1] + T[2:, 1:-1, 1:-1]
               + T[1:-1, :-2, 1:-1] + T[1:-1, 2:, 1:-1]
               + T[1:-1, 1:-1, :-2] + T[1:-1, 1:-1, 2:]
               - 6.0 * T[1:-1, 1:-1, 1:-1])
        return igg.update_halo_local(interior_add(T, 0.1 * lap))

    T, _ = _mkfields()
    T = igg.update_halo(T)
    for _ in range(3):
        T = step(T)
    igg.save_checkpoint(tmp_path / "mid.npz", T=T)
    for _ in range(3):
        T = step(T)
    ref = np.asarray(T)

    T2 = igg.load_checkpoint(tmp_path / "mid.npz")["T"]
    for _ in range(3):
        T2 = step(T2)
    np.testing.assert_array_equal(np.asarray(T2), ref)
    igg.finalize_global_grid()


def test_geometry_mismatch_rejected(tmp_path):
    igg.init_global_grid(6, 6, 6, quiet=True)
    T, _ = _mkfields()
    igg.save_checkpoint(tmp_path / "ck.npz", T=T)
    igg.finalize_global_grid()

    igg.init_global_grid(6, 6, 6, periodx=1, quiet=True)  # different periods
    with pytest.raises(igg.GridError, match="geometry mismatch"):
        igg.load_checkpoint(tmp_path / "ck.npz")
    igg.finalize_global_grid()

    igg.init_global_grid(8, 6, 6, quiet=True)  # different local size
    with pytest.raises(igg.GridError, match="geometry mismatch"):
        igg.load_checkpoint(tmp_path / "ck.npz")
    igg.finalize_global_grid()


class TestRedistribute:
    """load_checkpoint(redistribute=True): save on one decomposition,
    restore onto another with bit-identical interiors (VERDICT r3 item 8)."""

    @staticmethod
    def _save(tmp_path, periods):
        from helpers import encoded_field

        igg.init_global_grid(6, 6, 6, quiet=True, **periods)   # (2,2,2)
        T = igg.update_halo(encoded_field((6, 6, 6)))
        Vx = igg.update_halo(encoded_field((7, 6, 6)))         # staggered
        igg.save_checkpoint(tmp_path / "ck.npz", T=T, Vx=Vx)
        want = {k: np.asarray(igg.gather_interior(v))
                for k, v in (("T", T), ("Vx", Vx))}
        igg.finalize_global_grid()
        return want

    @pytest.mark.parametrize("periods", [
        dict(periodx=1, periody=1, periodz=1), dict(periody=1), {}])
    @pytest.mark.parametrize("target", [
        dict(dimx=1, dimy=1, dimz=1), dict(dimx=4, dimy=2, dimz=1)])
    def test_bit_identical_interiors(self, tmp_path, periods, target):
        want = self._save(tmp_path, periods)
        # Solve the target local sizes so the global domain matches the
        # (2,2,2) source (base 6, ol 2: interior per dim = 2*4 + 2*open):
        # n*(s-2) + 2*open == size  ->  s = (size - 2*open)/n + 2.
        local = []
        for d, (dkey, pkey) in enumerate((("dimx", "periodx"),
                                          ("dimy", "periody"),
                                          ("dimz", "periodz"))):
            open_b = not periods.get(pkey, 0)
            size = 2 * 4 + (2 if open_b else 0)    # source global interior
            n = target.get(dkey, 1)
            local.append((size - (2 if open_b else 0)) // n + 2)
        igg.init_global_grid(*local, quiet=True, **periods, **target)
        out = igg.load_checkpoint(tmp_path / "ck.npz", redistribute=True)
        for name in ("T", "Vx"):
            got = np.asarray(igg.gather_interior(out[name]))
            np.testing.assert_array_equal(got, want[name])
        # restored fields are live: a halo update must run
        igg.update_halo(out["T"])
        igg.finalize_global_grid()

    def test_periodicity_change_rejected(self, tmp_path):
        self._save(tmp_path, dict(periodx=1))
        igg.init_global_grid(10, 6, 6, dimx=1, dimy=1, dimz=1, quiet=True)
        with pytest.raises(igg.GridError, match="periodicity"):
            igg.load_checkpoint(tmp_path / "ck.npz", redistribute=True)
        igg.finalize_global_grid()

    def test_wrong_domain_rejected(self, tmp_path):
        self._save(tmp_path, dict(periodx=1))
        igg.init_global_grid(7, 7, 7, dimx=1, dimy=1, dimz=1, periodx=1,
                             quiet=True)
        with pytest.raises(igg.GridError, match="physical domain"):
            igg.load_checkpoint(tmp_path / "ck.npz", redistribute=True)
        igg.finalize_global_grid()


def test_misuse(tmp_path):
    igg.init_global_grid(6, 6, 6, quiet=True)
    with pytest.raises(igg.GridError, match="no fields"):
        igg.save_checkpoint(tmp_path / "ck.npz")
    T, _ = _mkfields()
    with pytest.raises(igg.GridError, match="reserved"):
        igg.save_checkpoint(tmp_path / "ck.npz", **{"__igg_meta__": T})
    igg.finalize_global_grid()


def test_bfloat16_and_path_and_names(tmp_path):
    import jax.numpy as jnp

    igg.init_global_grid(6, 6, 6, periodx=1, quiet=True)
    T = (igg.zeros((6, 6, 6), dtype=jnp.bfloat16)
         + jnp.asarray(3.5, jnp.bfloat16))
    # suffix-less path must round-trip to the exact path given, and a field
    # named "file" must not collide with np.savez internals
    igg.save_checkpoint(tmp_path / "ck", T=T, file=T)
    out = igg.load_checkpoint(tmp_path / "ck")
    assert out["T"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(out["T"], np.float32), np.asarray(T, np.float32))
    np.testing.assert_array_equal(
        np.asarray(out["file"], np.float32), np.asarray(T, np.float32))
    igg.finalize_global_grid()


def test_rank4_roundtrip_and_redistribute(tmp_path):
    """Rank-4 component-stacked fields checkpoint and redistribute like
    rank-3 ones (trailing dims unsharded)."""
    from helpers import encoded_field

    igg.init_global_grid(6, 6, 6, periodx=1, quiet=True)       # (2,2,2)
    U = igg.update_halo(encoded_field((6, 6, 6, 2)))
    igg.save_checkpoint(tmp_path / "r4.npz", U=U)
    out = igg.load_checkpoint(tmp_path / "r4.npz")["U"]
    np.testing.assert_array_equal(np.asarray(out), np.asarray(U))
    want = np.asarray(igg.gather_interior(U))
    igg.finalize_global_grid()

    igg.init_global_grid(10, 6, 6, dimx=1, dimy=2, dimz=2, periodx=1,
                         quiet=True)
    out = igg.load_checkpoint(tmp_path / "r4.npz", redistribute=True)["U"]
    np.testing.assert_array_equal(np.asarray(igg.gather_interior(out)), want)
    igg.finalize_global_grid()
