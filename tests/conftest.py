"""Test configuration: run the whole suite on a virtual 8-device CPU mesh.

This is the faithful analog of the reference's "multi-node without a cluster"
test strategy (`/root/reference/test/test_update_halo.jl:1-3`): the reference
runs its halo tests on one MPI process with periodic dims (self-neighbor
path), and transparently with any number of processes.  Here, 8 virtual CPU
devices exercise the real shard_map/ppermute code path — the same program
that runs on a TPU slice — without TPU hardware.
"""

import os

# Must happen before any JAX backend initializes.  XLA_FLAGS is read lazily
# at CPU-client creation; jax_platforms overrides the axon/TPU plugin that the
# environment force-registers via sitecustomize.
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

if os.environ.get("IGG_TPU_TESTS") == "1":
    # Escape hatch for the TPU-only tests (tests/test_mega_tpu.py): leave
    # the real backend in place.  Only run the TPU-marked files this way —
    # the rest of the suite expects the 8-device CPU mesh below.
    pass
else:
    jax.config.update("jax_platforms", "cpu")
    # The reference test suite works in Float64 (Julia default); enable x64
    # so the golden values transfer verbatim.  Library code itself is
    # dtype-agnostic.
    jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402

import igg  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_grid():
    """Each test starts and ends without an initialized grid (the reference
    re-runs each test file in a fresh process for the same reason,
    `/root/reference/test/runtests.jl:24`)."""
    if igg.grid_is_initialized():
        igg.finalize_global_grid()
    yield
    if igg.grid_is_initialized():
        igg.finalize_global_grid()


@pytest.fixture
def eight_devices():
    assert len(jax.devices()) == 8, (
        "test suite expects 8 virtual CPU devices; got "
        f"{len(jax.devices())} ({jax.devices()[0].platform})")
    return jax.devices()
