"""Round-11 satellites: the async-checkpoint donation hazard closed
(detection at the run loop AND at `_AsyncCheckpointWriter.submit`, sync
degrade with a one-time structured warning), member-targeted ChaosPlan
parsing, the fleet injectors composing under `igg.chaos.armed`, and the
IGG_ENSEMBLE_* / IGG_FLEET_* knobs in the typed env registry."""

import warnings

import numpy as np
import pytest

import igg
from igg.ops import interior_add


def _grid():
    igg.init_global_grid(6, 6, 6, periodx=1, periody=1, periodz=1,
                         quiet=True)


def _donating_step():
    @igg.sharded(donate_argnums=(0,))
    def step(T):
        lap = (T[:-2, 1:-1, 1:-1] + T[2:, 1:-1, 1:-1]
               + T[1:-1, :-2, 1:-1] + T[1:-1, 2:, 1:-1]
               + T[1:-1, 1:-1, :-2] + T[1:-1, 1:-1, 2:]
               - 6.0 * T[1:-1, 1:-1, 1:-1])
        return igg.update_halo_local(interior_add(T, 0.1 * lap))

    return lambda st: {"T": step(st["T"])}


def _state(seed=3):
    rng = np.random.default_rng(seed)
    T = igg.from_local_blocks(lambda c, ls: rng.standard_normal(ls),
                              (6, 6, 6))
    return {"T": igg.update_halo(T)}


# ---------------------------------------------------------------------------
# Donation hazard: async ring degrades to sync writes, warned once
# ---------------------------------------------------------------------------

def test_donating_step_degrades_async_ring_to_sync(tmp_path):
    """The documented hazard: a donating step_fn invalidates async
    snapshot buffers.  The loop detects the donation and degrades cadence
    generations to synchronous writes — one structured warning, no
    crashes, no silent garbage, and no ring generations lost once
    detected."""
    _grid()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        res = igg.run_resilient(_donating_step(), _state(), 20,
                                watch_every=5, checkpoint_dir=tmp_path,
                                checkpoint_every=5, ring=10)
    don = [x for x in w if "DONATES" in str(x.message)]
    assert len(don) == 1                       # one-time structured warning
    assert res.steps_done == 20
    cks = [e for e in res.events if e.kind == "checkpoint"]
    # Every committed generation after detection is a sync write (no
    # background label) and verifies.
    assert cks and not any(e.detail.get("background") for e in cks)
    from igg.checkpoint import list_generations
    steps = [s for s, _ in list_generations(tmp_path)]
    # Detection precedes the first async submit: zero generations lost.
    assert set(steps) >= {10, 15, 20}
    for _, p in list_generations(tmp_path):
        assert igg.verify_checkpoint(p)


def test_donation_probe_covers_every_field(tmp_path):
    """A step that donates T but passes Cp through — with Cp FIRST in the
    state dict — must still be detected (the probe checks every field,
    not just the dict's first value)."""
    from igg.ops import interior_add

    _grid()

    @igg.sharded(donate_argnums=(0,))
    def dstep(T, Cp):
        lap = (T[:-2, 1:-1, 1:-1] + T[2:, 1:-1, 1:-1]
               + T[1:-1, :-2, 1:-1] + T[1:-1, 2:, 1:-1]
               + T[1:-1, 1:-1, :-2] + T[1:-1, 1:-1, 2:]
               - 6.0 * T[1:-1, 1:-1, 1:-1])
        return igg.update_halo_local(
            interior_add(T, 0.0 * Cp[1:-1, 1:-1, 1:-1] * lap))

    rng = np.random.default_rng(5)
    Cp = igg.update_halo(igg.from_local_blocks(
        lambda c, ls: rng.standard_normal(ls), (6, 6, 6)))
    T = igg.update_halo(igg.from_local_blocks(
        lambda c, ls: rng.standard_normal(ls), (6, 6, 6)))

    def step_fn(st):
        return {"Cp": st["Cp"], "T": dstep(st["T"], st["Cp"])}

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        res = igg.run_resilient(step_fn, {"Cp": Cp, "T": T}, 20,
                                watch_every=5, watch_fields=["T"],
                                checkpoint_dir=tmp_path,
                                checkpoint_every=5, ring=10)
    assert len([x for x in w if "DONATES" in str(x.message)]) == 1
    assert res.steps_done == 20
    assert not any(e.kind == "checkpoint_failed" for e in res.events)
    assert not any(e.detail.get("background") for e in res.events
                   if e.kind == "checkpoint")


def test_writer_submit_detects_deleted_snapshot(tmp_path):
    """Direct users of _AsyncCheckpointWriter: a submit whose buffers were
    already donated fails that generation with a diagnosis (nothing valid
    to write), flips the writer to sync mode, and warns once; the next
    submit with live buffers is written synchronously."""
    import jax

    from igg.resilience import _AsyncCheckpointWriter

    _grid()
    saved = []

    def save_fn(step, fields, last_good):
        jax.block_until_ready(list(fields.values()))
        np.asarray(fields["T"])            # a deleted buffer would raise
        saved.append(step)
        return tmp_path / f"gen_{step}"

    writer = _AsyncCheckpointWriter(save_fn)
    step_fn = _donating_step()
    st = _state()
    dead = st["T"]
    st = step_fn(st)                       # donates -> `dead` deleted
    assert dead.is_deleted()

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        writer.submit(1, {"T": dead}, 0)       # already-invalid snapshot
        writer.submit(2, dict(st), 0)          # live buffers: sync write
        done, errs = writer.drain()
    writer.close()
    assert len([x for x in w if "DONATES" in str(x.message)]) == 1
    assert [e[0] for e in errs] == [1]
    assert "deleted" in str(errs[0][1])
    assert [d[0] for d in done] == [2]
    assert done[0][2] is False                 # sync-degraded, not background
    assert saved == [2]


def test_worker_detects_mid_flight_donation(tmp_path):
    """The worker finds a snapshot buffer deleted while waiting to fetch
    (the mid-flight donation shape): that generation fails with the
    donation diagnosis and the writer flips to sync mode for subsequent
    submits."""
    from igg.resilience import _AsyncCheckpointWriter

    _grid()

    def save_fn(step, fields, last_good):
        np.asarray(fields["T"])
        return tmp_path / f"gen_{step}"

    class _Gated:
        """A snapshot stand-in that reports not-ready until 'donated',
        then deleted — deterministic ordering for the worker's poll."""

        def __init__(self):
            self.deleted = False

        def is_ready(self):
            # The first poll observes in-flight work; the caller deletes
            # before the next poll.
            self.deleted = True
            return False

        def is_deleted(self):
            return self.deleted

    writer = _AsyncCheckpointWriter(save_fn)
    writer.submit(1, {"T": _Gated()}, 0)
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        done, errs = writer.drain()
    writer.close()
    assert [e[0] for e in errs] == [1]
    assert "deleted" in str(errs[0][1]).lower()
    assert writer._donation_seen


# ---------------------------------------------------------------------------
# Chaos: member-targeted entries + fleet injectors compose under armed()
# ---------------------------------------------------------------------------

def test_member_targeted_chaos_parsing():
    plan = igg.chaos.ChaosPlan(nan_at=[(3, "T"), (4, "T", (1, 2, 3)),
                                       (5, 2, "T"), (6, 0, "T", (2, 2, 2))])
    assert plan.nan_at == ((3, None, "T", None), (4, None, "T", (1, 2, 3)),
                           (5, 2, "T", None), (6, 0, "T", (2, 2, 2)))
    with pytest.raises(igg.GridError, match="member-targeted"):
        igg.chaos.ChaosPlan(nan_at=[(3, 1)])


def test_member_poison_hits_only_that_lane():
    _grid()
    import jax

    from igg.chaos import _poison

    stacked = jax.device_put(np.zeros((4, 12, 12, 12)))
    out = np.asarray(_poison(stacked, None, member=2))
    assert np.isnan(out[2]).sum() == 1
    assert np.isfinite(out[[0, 1, 3]]).all()
    with pytest.raises(igg.GridError, match="out of range"):
        _poison(stacked, None, member=7)


def test_fleet_injectors_compose_under_armed():
    from igg import fleet

    assert fleet._CHAOS_JOB_TAP is None
    with igg.chaos.armed(igg.chaos.scheduler_fault("a", times=2),
                         igg.chaos.job_preempt_at("b", 7)) as (sf, jp):
        tap = fleet._CHAOS_JOB_TAP
        assert tap["fault"]["a"]["times"] == 2
        assert tap["preempt"]["b"]["step"] == 7
    assert fleet._CHAOS_JOB_TAP is None        # exception-safe disarm


# ---------------------------------------------------------------------------
# Env registry: the new knobs are known (and typed)
# ---------------------------------------------------------------------------

def test_ensemble_fleet_knobs_registered(monkeypatch):
    from igg import _env

    for name in ("IGG_ENSEMBLE_RETRIES", "IGG_ENSEMBLE_MAX_PENDING_PROBES",
                 "IGG_FLEET_RETRIES", "IGG_FLEET_BACKOFF"):
        assert name in _env._KNOWN
    # Setting them trips no unrecognized-knob warning...
    monkeypatch.setattr(_env, "_warned_unknown", False)
    monkeypatch.setenv("IGG_FLEET_RETRIES", "5")
    monkeypatch.setenv("IGG_ENSEMBLE_RETRIES", "1")
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert _env.integer("IGG_FLEET_RETRIES", 2) == 5
    # ...and the accessors are typed: junk raises GridError naming the var.
    monkeypatch.setenv("IGG_FLEET_BACKOFF", "soon")
    with pytest.raises(igg.GridError, match="IGG_FLEET_BACKOFF"):
        _env.number("IGG_FLEET_BACKOFF", 0.5)
    # The defaults feed the tiers.
    from igg.ensemble import _member_retries_default
    from igg.fleet import _fleet_retries_default

    assert _member_retries_default() == 1
    assert _fleet_retries_default() == 5