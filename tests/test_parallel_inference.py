"""sharded() inference and caching regression tests."""

import numpy as np

import igg
from igg import parallel


def test_non_grid_output_is_replicated_not_concatenated():
    igg.init_global_grid(6, 6, 6, periodx=1, periody=1, periodz=1, quiet=True)
    import jax.numpy as jnp

    @igg.sharded
    def step(T):
        # small diagnostics vector: must come back replicated, not
        # concatenated over gx into shape (24,)
        return T + 1.0, jnp.zeros((3,)) + 7.0

    T = igg.zeros((6, 6, 6))
    T2, diag = step(T)
    assert T2.shape == T.shape
    assert diag.shape == (3,)
    assert np.allclose(np.array(diag), 7.0)


def test_staggered_and_flux_outputs_still_sharded():
    igg.init_global_grid(6, 6, 6, periodx=1, periody=1, periodz=1, quiet=True)

    @igg.sharded
    def step(T):
        qx = T[1:, 1:-1, 1:-1] - T[:-1, 1:-1, 1:-1]   # (5,4,4) local
        return qx

    T = igg.zeros((6, 6, 6))
    qx = step(T)
    assert qx.shape == (2 * 5, 2 * 4, 2 * 4)


def test_replicated_grid_shaped_output_raises_demanding_out_specs():
    """VERDICT weak #4: a replicated diagnostic that happens to be
    (nx,ny,nz)-shaped must fail loudly, not be silently concatenated into a
    wrong 'global' array."""
    import jax.numpy as jnp
    import pytest

    igg.init_global_grid(6, 6, 6, periodx=1, periody=1, periodz=1, quiet=True)

    @igg.sharded
    def step(T):
        # Device-invariant but grid-block shaped: genuinely ambiguous.
        return T + 1.0, jnp.full((6, 6, 6), 7.0)

    T = igg.zeros((6, 6, 6))
    with pytest.raises(igg.GridError, match="identical on every device"):
        step(T)


def test_replicated_grid_shaped_output_with_explicit_out_specs():
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    igg.init_global_grid(6, 6, 6, periodx=1, periody=1, periodz=1, quiet=True)

    @igg.sharded(out_specs=(igg.spec_for(3), P()))
    def step(T):
        return T + 1.0, jnp.full((6, 6, 6), 7.0)

    T = igg.zeros((6, 6, 6))
    T2, diag = step(T)
    assert T2.shape == T.shape
    assert diag.shape == (6, 6, 6)
    assert np.allclose(np.asarray(diag), 7.0)


def test_device_varying_non_grid_output_raises():
    """A per-device value that is not grid-block shaped (e.g. a per-device
    scalar diagnostic) is ambiguous: demand out_specs / a reduction."""
    import pytest
    from jax import lax

    igg.init_global_grid(6, 6, 6, periodx=1, periody=1, periodz=1, quiet=True)

    @igg.sharded
    def step(T):
        return T + 1.0, lax.axis_index("gx") * 1.0

    T = igg.zeros((6, 6, 6))
    with pytest.raises(igg.GridError, match="differ per device"):
        step(T)


def test_psum_reduced_diagnostic_is_replicated():
    """The documented fix for per-device diagnostics: reduce over the mesh.
    The taint pass recognizes a full-mesh psum as device-invariant, so the
    error message's advice works without explicit out_specs."""
    from jax import lax

    igg.init_global_grid(6, 6, 6, periodx=1, periody=1, periodz=1, quiet=True)

    @igg.sharded
    def step(T):
        r = lax.psum((T ** 2).sum(), igg.AXIS_NAMES)
        return T + 1.0, r

    T = igg.ones((6, 6, 6))
    T2, norm2 = step(T)
    assert float(norm2) == 6 * 6 * 6 * 8  # 8 devices x 216 ones


def test_pmax_reduced_diagnostic_is_replicated():
    """Max/min-norm diagnostics reduce with pmax/pmin (psum would be
    numerically wrong); the untaint rule covers them the same way."""
    from jax import lax

    igg.init_global_grid(6, 6, 6, periodx=1, periody=1, periodz=1, quiet=True)

    @igg.sharded
    def step(T):
        return T + 1.0, lax.pmax(T.max(), igg.AXIS_NAMES), \
            lax.pmin(T.min(), igg.AXIS_NAMES)

    T = igg.ones((6, 6, 6))
    _, hi, lo = step(T)
    assert float(hi) == 1.0 and float(lo) == 1.0


def test_recreated_closures_share_compiled_program():
    igg.init_global_grid(6, 6, 6, periodx=1, periody=1, periodz=1, quiet=True)
    from igg.models import diffusion3d as d3
    params = d3.Params()
    T, Cp = d3.init_fields(params, dtype=np.float32)
    n0 = len(parallel._compiled)
    for _ in range(3):
        step = d3.make_step(params, donate=False)  # fresh closure each time
        T = step(T, Cp)
    assert len(parallel._compiled) == n0 + 1  # one shared program


def test_models_namespace_exports_wave2d():
    import igg.models
    assert hasattr(igg.models, "wave2d") and hasattr(igg.models, "diffusion3d")


def test_compiled_cache_is_bounded(monkeypatch):
    """VERDICT round-1 weak #5: closures over unhashable captures fall back
    to identity keys; the LRU bound keeps that from leaking one compiled
    program per make_step()-style call forever."""
    from igg import parallel

    igg.init_global_grid(6, 6, 6, periodx=1, periody=1, periodz=1, quiet=True)
    monkeypatch.setattr(parallel, "_CACHE_CAP", 6)
    parallel.free_sharded_cache()
    T = igg.zeros((6, 6, 6))

    def make(cfg):
        # True closure over an unhashable dict -> identity cache key.
        @igg.sharded
        def step(T):
            return T + cfg["dt"]

        return step

    for i in range(10):
        T = make({"dt": 0.1})(T)
    assert len(parallel._compiled) <= 6
