"""Finalize + select_device tests
(`/root/reference/test/test_finalize_global_grid.jl`,
`/root/reference/test/test_select_device.jl`)."""

import pytest

import igg
from igg import halo


def test_finalize_clears_everything():
    igg.init_global_grid(6, 6, 6, periodx=1, quiet=True)
    A = igg.zeros((6, 6, 6))
    igg.update_halo(A)
    assert len(halo._compiled) > 0
    igg.finalize_global_grid()
    assert not igg.grid_is_initialized()
    assert len(halo._compiled) == 0


def test_double_finalize_errors():
    igg.init_global_grid(4, 4, 4, quiet=True)
    igg.finalize_global_grid()
    with pytest.raises(igg.GridError):
        igg.finalize_global_grid()


def test_select_device():
    igg.init_global_grid(4, 4, 4, quiet=True)
    assert isinstance(igg.select_device(), int)


def test_select_device_requires_init():
    with pytest.raises(igg.GridError):
        igg.select_device()


def test_reinit_after_finalize():
    igg.init_global_grid(4, 4, 4, quiet=True)
    igg.finalize_global_grid()
    me, dims, nprocs, *_ = igg.init_global_grid(6, 6, 6, quiet=True)
    assert nprocs == 8
    A = igg.zeros((6, 6, 6))
    igg.update_halo(A)
