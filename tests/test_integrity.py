"""The numeric-integrity layer (igg/integrity.py) and its round-19
satellites: silent-data-corruption defense end to end — invariant
probes fused into the watchdog probe (finite-but-wrong state the NaN
watchdog provably cannot see, detected within one watch window with
per-rank device attribution), shadow re-execution spot checks for
corruption with no declared invariant, verified-generation rollback
(`verify_checkpoint(deep=True)` refusing poisoned-but-finite
generations the structural scan serves), the heal loop's
fence-the-suspect re-tile, recurrence demotion of a finitely-
miscompiling tier, the chaos injectors (`silent_corruption`,
`poison_checkpoint`), deep-verify coverage across formats (flat npz,
sharded dirs, bf16, elastic restore, mixed stamped/unstamped rings,
pre-round-19 backward compat), the per-member ensemble rows, the
registry hook, the statusd readiness reason, and the env knobs."""

import json

import numpy as np
import pytest

import igg
from igg import chaos
from igg import checkpoint as ck
from igg import integrity as integ
from igg import telemetry as tel


@pytest.fixture(autouse=True)
def _clean_observability():
    """Metrics, the flight ring, and the perf ledger are process-global;
    isolate every test (the test_heal fixture's pattern).  The chaos
    state tap is module-global too — a failed test must not leak an
    armed injector."""
    tel.reset_metrics()
    tel._ring().clear()
    igg.perf.reset()
    yield
    from igg import resilience as res_mod

    res_mod._CHAOS_STATE_TAP = None
    for s in list(tel._SESSIONS):
        s.detach()
    with tel._lock:
        tel._SUBSCRIBERS.clear()
    tel.reset_metrics()
    igg.perf.reset()
    igg.degrade.reset()


def _grid(n=6, **kw):
    args = dict(periodx=1, periody=1, periodz=1, quiet=True)
    args.update(kw)
    igg.init_global_grid(n, n, n, **args)


def _make_step():
    from igg.ops import interior_add

    @igg.sharded
    def step(T):
        lap = (T[:-2, 1:-1, 1:-1] + T[2:, 1:-1, 1:-1]
               + T[1:-1, :-2, 1:-1] + T[1:-1, 2:, 1:-1]
               + T[1:-1, 1:-1, :-2] + T[1:-1, 1:-1, 2:]
               - 6.0 * T[1:-1, 1:-1, 1:-1])
        return igg.update_halo_local(interior_add(T, 0.1 * lap))

    return lambda st: {"T": step(st["T"])}


def _init_state(n=6, seed=3):
    rng = np.random.default_rng(seed)
    T = igg.from_local_blocks(lambda c, ls: rng.standard_normal(ls),
                              (n, n, n))
    return {"T": igg.update_halo(T)}


def _heat_cfg(**kw):
    kw.setdefault("check_every", 0)
    return integ.IntegrityConfig(
        invariants=[integ.Invariant("total_heat", ("T",), moment=1,
                                    kind="conserved")], **kw)


def _reference(nt, n=6):
    step_fn = _make_step()
    st = _init_state(n)
    for _ in range(nt):
        st = step_fn(st)
    return np.asarray(st["T"])


# ---------------------------------------------------------------------------
# (i) invariant probes: detection, attribution, verified rollback
# ---------------------------------------------------------------------------

def test_invariant_detects_finite_corruption_nan_watchdog_silent(tmp_path):
    """The headline contract: a FINITE perturbation (the NaN watchdog
    provably silent) is detected by the conserved-sum probe within one
    watch window, attributed to the injected rank's device by the
    per-rank partials, rolled back, and the run finishes bit-exact."""
    _grid()
    ref = _reference(60)
    with chaos.silent_corruption("T", step=27, magnitude=25.0, rank=3):
        res = igg.run_resilient(_make_step(), _init_state(), 60,
                                watch_every=5, checkpoint_dir=tmp_path,
                                checkpoint_every=10,
                                integrity=_heat_cfg(),
                                install_sigterm=False)
    kinds = [e.kind for e in res.events]
    assert "nan_detected" not in kinds
    viol = next(e for e in res.events if e.kind == "integrity_violation")
    assert viol.step == 30                       # next watch boundary
    assert viol.detail["source"] == "invariant"
    assert viol.detail["invariant"] == "total_heat"
    assert viol.detail["rank"] == 3
    assert viol.detail["partials"][3] == max(viol.detail["partials"])
    rb = next(e for e in res.events if e.kind == "rollback")
    assert rb.step < viol.step
    assert kinds.index("rollback") < kinds.index("integrity_resolved")
    assert res.retries == 1
    assert np.array_equal(np.asarray(res.state["T"]), ref)


def test_rollback_skips_poisoned_generation_via_deep_verify(tmp_path):
    """The finite-but-poisoned window: a cadence generation written
    BETWEEN the corruption and its detection passes check_finite but
    fails deep verification (its invariant drifted against the stamped
    reference) — the rollback scan must land on the older verified
    generation, never the poisoned one."""
    _grid()
    ref = _reference(60)
    # checkpoint_every=5 == watch_every guarantees a generation at the
    # corrupted-but-undetected step 25 (injection at 23, detection at
    # the step-25 probe, cadence write at 25 submitted before the fetch).
    with chaos.silent_corruption("T", step=23, magnitude=25.0, rank=1):
        res = igg.run_resilient(_make_step(), _init_state(), 60,
                                watch_every=5, checkpoint_dir=tmp_path,
                                checkpoint_every=5,
                                integrity=_heat_cfg(),
                                max_pending_probes=8,
                                install_sigterm=False)
    viol = next(e for e in res.events if e.kind == "integrity_violation")
    rb = next(e for e in res.events if e.kind == "rollback")
    assert rb.step < viol.step <= 30
    assert np.array_equal(np.asarray(res.state["T"]), ref)


def test_poisoned_generation_matrix_structural_serves_deep_refuses(
        tmp_path):
    """The satellite resilience-matrix proof, offline: poison_checkpoint
    writes finite corruption CONSISTENTLY through the CRC layer on both
    formats — the non-deep scan serves the poisoned generation, the deep
    scan skips it (and pre-poison generations still deep-verify)."""
    _grid()
    st = _init_state()
    igg.save_checkpoint_sharded(tmp_path / "ckpt_000000010", **st)
    igg.save_checkpoint_sharded(tmp_path / "ckpt_000000020", **st)
    igg.save_checkpoint(tmp_path / "ckpt_000000030.npz", **st)
    chaos.poison_checkpoint(tmp_path / "ckpt_000000020", magnitude=5.0,
                            shard=2)
    chaos.poison_checkpoint(tmp_path / "ckpt_000000030.npz", magnitude=5.0)
    # Structural + finite verification passes the poisoned artifacts...
    assert ck.verify_checkpoint(tmp_path / "ckpt_000000020",
                                check_finite=True)
    assert ck.verify_checkpoint(tmp_path / "ckpt_000000030.npz",
                                check_finite=True)
    # ...and the corrupted values really did land (the CRC layer was
    # rewritten, not bypassed).
    loaded = igg.load_checkpoint(tmp_path / "ckpt_000000020")
    assert not np.array_equal(np.asarray(loaded["T"]), np.asarray(st["T"]))
    # Deep verification refuses exactly the poisoned ones.
    assert not ck.verify_checkpoint(tmp_path / "ckpt_000000020", deep=True)
    assert not ck.verify_checkpoint(tmp_path / "ckpt_000000030.npz",
                                    deep=True)
    assert ck.verify_checkpoint(tmp_path / "ckpt_000000010", deep=True)
    assert ck.latest_checkpoint(tmp_path, "ckpt", check_finite=True) \
        == tmp_path / "ckpt_000000030.npz"
    assert ck.latest_checkpoint(tmp_path, "ckpt", check_finite=True,
                                deep=True) == tmp_path / "ckpt_000000010"


def test_shadow_check_catches_corruption_with_no_invariant(tmp_path):
    """Mechanism 2: with NO declared invariant, the shadow re-execution
    spot check (window re-dispatched from the device-resident entry
    snapshot, |state − truth| compared on device) catches the silent
    corruption — including one struck inside the very first window."""
    _grid()
    ref = _reference(60)
    cfg = integ.IntegrityConfig(invariants=[], check_every=1)
    with chaos.silent_corruption("T", step=2, magnitude=10.0, rank=5):
        res = igg.run_resilient(_make_step(), _init_state(), 60,
                                watch_every=5, checkpoint_dir=tmp_path,
                                checkpoint_every=10, integrity=cfg,
                                install_sigterm=False)
    viol = next(e for e in res.events if e.kind == "integrity_violation")
    assert viol.detail["source"] == "shadow"
    assert viol.step == 5 and viol.detail["rank"] == 5
    assert np.array_equal(np.asarray(res.state["T"]), ref)


def test_shadow_amortization_cadence(tmp_path):
    """check_every=N shadows every N-th window only (the 1/check_every
    cost contract): the monitor's shadow counter proves the cadence."""
    _grid()
    cfg = _heat_cfg(check_every=3)
    captured = {}
    orig = integ.Monitor.dispatch

    def spy(self, *a, **kw):
        captured["mon"] = self
        return orig(self, *a, **kw)

    integ.Monitor.dispatch = spy
    try:
        igg.run_resilient(_make_step(), _init_state(), 60, watch_every=5,
                          integrity=cfg, install_sigterm=False)
    finally:
        integ.Monitor.dispatch = orig
    mon = captured["mon"]
    # 12 windows; snapshots at windows 0 (entry), 3, 6, 9 -> 4 shadows.
    assert mon.shadow_checks == 4
    assert mon.checks == 12


def test_heal_fences_attributed_device_and_retiles_bit_exact(tmp_path):
    """The closed loop: an attributed violation plans rollback-to-
    verified plus a fence-the-SUSPECT-device re-tile — the chip named by
    the per-rank partials leaves the serving set, and the healed run's
    de-duplicated interior is bitwise the uninterrupted reference."""
    from igg import heal as iheal

    nt = 60
    _grid()
    dims0 = igg.get_global_grid().dims
    step_fn = _make_step()
    st = _init_state()
    for _ in range(nt):
        st = step_fn(st)
    ref = igg.gather_interior(st["T"])
    igg.finalize_global_grid()

    _grid()
    eng = iheal.HealEngine(iheal.HealPolicy(cooldown_s=0.0),
                           run="resilient")
    with chaos.silent_corruption("T", step=27, magnitude=25.0, rank=3):
        res = igg.run_resilient(_make_step(), _init_state(), nt,
                                watch_every=5, checkpoint_dir=tmp_path,
                                checkpoint_every=10,
                                integrity=_heat_cfg(), heal=eng,
                                install_sigterm=False)
    viol = next(e for e in res.events if e.kind == "integrity_violation")
    retile = next(e for e in res.events if e.kind == "heal_retile")
    assert retile.detail["reason"] == "integrity_violation"
    g2 = igg.get_global_grid()
    assert g2.dims != dims0
    live = [str(d) for d in g2.mesh.devices.flat]
    assert viol.detail["device"] not in live
    assert np.array_equal(igg.gather_interior(res.state["T"]), ref)


def test_recurrent_violation_demotes_finitely_miscompiling_tier(tmp_path):
    """The PR-5 deterministic-miscompile signature, generalized: a
    kernel tier corrupted by a FINITE magnitude produces wrong physics
    the NaN watchdog never sees; the shadow check against the declared
    TRUTH tier raises the same violation at the same step after a
    bit-exact rollback, and the recurrence rung demotes the serving
    tier — the truth rung finishes the run bit-exactly with no retry
    burned on the recurrence (and the demotion re-anchors the integrity
    references, so the healthy replay is never flagged against the
    miscompiled trajectory)."""
    from igg.models import diffusion3d as d3

    nv = 8
    igg.init_global_grid(nv, nv, 128, dimx=1, dimy=1, dimz=1, periodx=1,
                         periody=1, periodz=1, quiet=True)
    params = d3.Params()
    T0, Cp = d3.init_fields(params, dtype=np.float32)

    def make_state():
        return {"T": T0, "Cp": Cp}

    truth = d3.make_step(params, donate=False, use_pallas=False)

    def truth_fn(s):
        return {"T": truth(s["T"], s["Cp"]), "Cp": s["Cp"]}

    st = make_state()
    for _ in range(20):
        st = truth_fn(st)
    ref = np.asarray(st["T"])

    igg.degrade.reset()
    cfg = integ.IntegrityConfig(invariants=[], check_every=1,
                                truth_step_fn=truth_fn)
    with chaos.kernel_corrupt("diffusion3d.mosaic", magnitude=1e4):
        step = d3.make_step(params, donate=False, pallas_interpret=True)
        step_fn = lambda s: {"T": step(s["T"], s["Cp"]), "Cp": s["Cp"]}
        res = igg.run_resilient(step_fn, make_state(), 20,
                                watch_every=5, checkpoint_dir=tmp_path,
                                checkpoint_every=5, integrity=cfg,
                                install_sigterm=False)
    kinds = [e.kind for e in res.events]
    assert "nan_detected" not in kinds
    assert kinds.count("integrity_violation") >= 2
    demo = next(e for e in res.events if e.kind == "tier_degraded")
    assert demo.detail["tier"] == "diffusion3d.mosaic"
    assert demo.detail["reason"] == "nan_recurrence"
    assert igg.degrade.active().get("diffusion3d") == "diffusion3d.xla"
    assert res.retries == 1            # the demotion burned no retry
    assert np.array_equal(np.asarray(res.state["T"]), ref)


def test_nan_counts_stay_field_aligned_with_nonfloat_watch(tmp_path):
    """Monitor keeps the FULL watch list (non-float fields get a zero
    count row, the plain-probe contract): a NaN verdict under integrity
    must name the field that actually blew up, not a zipped-off
    neighbor."""
    import jax.numpy as jnp

    _grid()
    base = _make_step()
    mask = igg.from_local_blocks(
        lambda c, ls: np.ones(ls, dtype=np.int32), (6, 6, 6))

    def step_fn(st):
        return {"mask": st["mask"], **base({"T": st["T"]})}

    st = {"T": _init_state()["T"], "mask": mask}
    with pytest.raises(igg.ResilienceError) as ei:
        igg.run_resilient(step_fn, st, 20, watch_every=5,
                          watch_fields=["mask", "T"],
                          integrity=_heat_cfg(),
                          chaos=chaos.ChaosPlan(nan_at=[(7, "T")]),
                          install_sigterm=False)
    ev = next(e for e in ei.value.events if e.kind == "nan_detected")
    assert list(ev.detail["counts"]) == ["T"], ev.detail


def test_silent_corruption_composes_under_armed():
    """armed() drives arm/disarm for the new injector like any other,
    and a consumed injector re-arms on re-entry."""
    from igg import resilience as res_mod

    inj = chaos.silent_corruption("T", step=3, magnitude=1.0)
    with chaos.armed(inj) as got:
        assert got is inj
        assert res_mod._CHAOS_STATE_TAP is not None
    assert res_mod._CHAOS_STATE_TAP is None
    inj._fired = True
    inj.arm()
    assert inj._fired is False        # arming re-arms the one-shot
    inj.disarm()


def test_config_validation_and_knob_registration():
    _grid()
    with pytest.raises(igg.GridError, match="watch cadence"):
        igg.run_resilient(_make_step(), _init_state(), 10, watch_every=0,
                          integrity=_heat_cfg(), install_sigterm=False)
    with pytest.raises(igg.GridError, match="not in"):
        igg.run_resilient(
            _make_step(), _init_state(), 10, watch_every=5,
            integrity=integ.IntegrityConfig(invariants=[
                integ.Invariant("x", ("missing",))]),
            install_sigterm=False)
    with pytest.raises(igg.GridError, match="integrity="):
        integ.as_config("yes")
    with pytest.raises(igg.GridError, match="moment"):
        integ.Invariant("bad", ("T",), moment=3)
    from igg import _env

    for knob in ("IGG_INTEGRITY", "IGG_INTEGRITY_CHECK_EVERY",
                 "IGG_INTEGRITY_TOL", "IGG_INTEGRITY_DEEP_VERIFY"):
        assert knob in _env._KNOWN


def test_env_knob_drives_default(tmp_path, monkeypatch):
    """integrity=None is IGG_INTEGRITY-driven (the telemetry= pattern);
    False wins over the env knob."""
    _grid()
    monkeypatch.setenv("IGG_INTEGRITY", "1")
    monkeypatch.setenv("IGG_INTEGRITY_CHECK_EVERY", "0")
    res = igg.run_resilient(_make_step(), _init_state(), 10, watch_every=5,
                            telemetry=tmp_path, install_sigterm=False)
    recs = [json.loads(l) for l in
            (tmp_path / "events_r0.jsonl").read_text().splitlines()]
    assert any(r["kind"] == "integrity_config" for r in recs)
    assert integ.as_config(False) is None


# ---------------------------------------------------------------------------
# (ii) deep verification across formats (satellite 6)
# ---------------------------------------------------------------------------

def test_deep_verify_flat_and_sharded_roundtrip(tmp_path):
    _grid()
    st = _init_state()
    igg.save_checkpoint(tmp_path / "flat_000000001.npz", **st)
    igg.save_checkpoint_sharded(tmp_path / "gen_000000001", **st)
    for p in (tmp_path / "flat_000000001.npz", tmp_path / "gen_000000001"):
        assert ck.verify_checkpoint(p, check_finite=True, deep=True)
    # Flat meta and sharded manifest stamp IDENTICAL dedup sums.
    with np.load(tmp_path / "flat_000000001.npz") as z:
        meta = json.loads(bytes(z["__igg_meta__"].tobytes()).decode())
    man = json.loads(
        (tmp_path / "gen_000000001" / "manifest.json").read_text())
    # Equal to the last ulp: the flat path sums strided views of the
    # stacked array, the sharded path contiguous fetched blocks — numpy
    # pairwise summation may split the two differently.
    assert np.allclose(meta["deep"]["sums"]["T"], man["deep"]["sums"]["T"],
                       rtol=1e-12, atol=0.0)


def test_deep_verify_bf16_fields(tmp_path):
    import jax.numpy as jnp

    _grid()
    T = _init_state()["T"].astype(jnp.bfloat16)
    igg.save_checkpoint_sharded(tmp_path / "gen_000000001", T=T)
    igg.save_checkpoint(tmp_path / "flat_000000001.npz", T=T)
    assert ck.verify_checkpoint(tmp_path / "gen_000000001", deep=True)
    assert ck.verify_checkpoint(tmp_path / "flat_000000001.npz", deep=True)
    chaos.poison_checkpoint(tmp_path / "gen_000000001", magnitude=4.0,
                            shard=1)
    assert ck.verify_checkpoint(tmp_path / "gen_000000001",
                                check_finite=True)
    assert not ck.verify_checkpoint(tmp_path / "gen_000000001", deep=True)


def test_deep_verified_generation_restores_elastically(tmp_path):
    """redistribute=True restore of a deep-verified generation onto a
    different decomposition is bit-exact — the deep stamps describe the
    de-duplicated PHYSICS, which is decomposition-invariant."""
    _grid()
    st = _init_state()
    stacked = np.asarray(st["T"])
    igg.save_checkpoint_sharded(tmp_path / "gen_000000001", **st)
    assert ck.verify_checkpoint(tmp_path / "gen_000000001", deep=True)
    interior_ref = igg.gather_interior(st["T"])
    igg.finalize_global_grid()
    igg.init_global_grid(10, 10, 6, dimx=1, dimy=1, dimz=2, periodx=1,
                         periody=1, periodz=1, quiet=True)
    loaded = igg.load_checkpoint(tmp_path / "gen_000000001",
                                 redistribute=True)
    assert np.array_equal(igg.gather_interior(loaded["T"]), interior_ref)
    # And a generation re-saved under the NEW decomposition deep-verifies
    # with the SAME dedup sums (different shard partials, same physics).
    igg.save_checkpoint_sharded(tmp_path / "gen_000000002", **loaded)
    assert ck.verify_checkpoint(tmp_path / "gen_000000002", deep=True)
    m1 = json.loads(
        (tmp_path / "gen_000000001" / "manifest.json").read_text())
    m2 = json.loads(
        (tmp_path / "gen_000000002" / "manifest.json").read_text())
    assert np.allclose(m1["deep"]["sums"]["T"], m2["deep"]["sums"]["T"],
                       rtol=1e-12)
    del stacked


def _strip_deep(gen):
    """Rewind a generation to its pre-round-19 shape: no deep stamps in
    the manifest or shard metas (the backward-compat fixture)."""
    import pathlib

    gen = pathlib.Path(gen)
    if gen.is_dir():
        mp = gen / "manifest.json"
        man = json.loads(mp.read_text())
        man.pop("deep", None)
        from igg.checkpoint import (_META_KEY, _shard_name, _summary_crc,
                                    _write_atomic_text, _write_npz)

        for name in list(man["shards"]):
            sp = gen / name
            with np.load(sp) as z:
                smeta = json.loads(bytes(z[_META_KEY].tobytes()).decode())
                arrays = {k: z[k] for k in z.files if k != _META_KEY}
            smeta.pop("deep", None)
            _write_npz(sp, {**arrays, _META_KEY: np.frombuffer(
                json.dumps(smeta).encode(), dtype=np.uint8)})
        _write_atomic_text(mp, json.dumps(man))
        return
    from igg.checkpoint import _META_KEY, _write_npz

    with np.load(gen) as z:
        meta = json.loads(bytes(z[_META_KEY].tobytes()).decode())
        arrays = {k: z[k] for k in z.files if k != _META_KEY}
    meta.pop("deep", None)
    _write_npz(gen, {**arrays, _META_KEY: np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8)})


def test_mixed_stamped_unstamped_ordering_and_backward_compat(tmp_path):
    """Pre-round-19 generations (no deep stamp) load and scan unchanged;
    a deep=True scan skips them (deep cannot vouch), so a 'prefer
    deep' caller falls back to the newest stamped one — the mixed-ring
    ordering contract."""
    _grid()
    st = _init_state()
    igg.save_checkpoint_sharded(tmp_path / "ckpt_000000010", **st)
    igg.save_checkpoint_sharded(tmp_path / "ckpt_000000020", **st)
    igg.save_checkpoint(tmp_path / "ckpt_000000030.npz", **st)
    _strip_deep(tmp_path / "ckpt_000000020")
    _strip_deep(tmp_path / "ckpt_000000030.npz")
    # Backward compat: unstamped artifacts verify structurally, load
    # bit-exactly, and still win the PLAIN scan.
    for p in ("ckpt_000000020", "ckpt_000000030.npz"):
        assert ck.verify_checkpoint(tmp_path / p, check_finite=True)
        loaded = igg.load_checkpoint(tmp_path / p)
        assert np.array_equal(np.asarray(loaded["T"]), np.asarray(st["T"]))
        assert not ck.verify_checkpoint(tmp_path / p, deep=True)
    assert ck.latest_checkpoint(tmp_path, "ckpt", check_finite=True) \
        == tmp_path / "ckpt_000000030.npz"
    assert ck.latest_checkpoint(tmp_path, "ckpt", check_finite=True,
                                deep=True) == tmp_path / "ckpt_000000010"
    # A resume over the mixed ring under integrity (deep preference)
    # lands on the stamped generation.
    res = igg.run_resilient(_make_step(), _init_state(), 10, watch_every=5,
                            checkpoint_dir=tmp_path, prefix="ckpt",
                            checkpoint_every=0, resume=True,
                            integrity=_heat_cfg(),
                            install_sigterm=False)
    resume = next(e for e in res.events if e.kind == "resume")
    assert resume.step == 10
    assert resume.detail["path"].endswith("ckpt_000000010")


def test_open_boundary_owned_planes_in_deep_stamp(tmp_path):
    """Open-boundary user-owned halo planes are de-duplicated global
    cells: the deep stamp covers them (a perturbation there is caught),
    and the stamp round-trips on mixed periodicity."""
    _grid(periodx=0)
    st = _init_state()
    igg.save_checkpoint_sharded(tmp_path / "gen_000000001", **st)
    assert ck.verify_checkpoint(tmp_path / "gen_000000001", deep=True)
    chaos.poison_checkpoint(tmp_path / "gen_000000001", magnitude=3.0,
                            shard=7)
    assert not ck.verify_checkpoint(tmp_path / "gen_000000001", deep=True)


# ---------------------------------------------------------------------------
# (iii) the ensemble tier: per-member invariant rows
# ---------------------------------------------------------------------------

def test_ensemble_member_violation_isolated_and_bit_exact(tmp_path):
    """A finite per-member corruption raises integrity_violation
    attributed to the LANE; only that lane rolls back and replays —
    every member finishes bit-exact vs an uninterrupted ensemble, no
    quarantine, healthy lanes untouched."""
    import sys

    sys.path.insert(0, "tests")
    from helpers import ensemble_member_step, ensemble_states

    _grid()
    clean = igg.run_ensemble(ensemble_member_step(), ensemble_states(4),
                             40, watch_every=5, install_sigterm=False)
    igg.finalize_global_grid()

    _grid()
    with chaos.silent_corruption("T", step=22, magnitude=30.0, member=2):
        res = igg.run_ensemble(ensemble_member_step(), ensemble_states(4),
                               40, watch_every=5, checkpoint_dir=tmp_path,
                               checkpoint_every=10,
                               integrity=_heat_cfg(),
                               install_sigterm=False)
    kinds = [e.kind for e in res.events]
    assert "member_diverged" not in kinds       # the NaN rows stayed silent
    viol = next(e for e in res.events if e.kind == "integrity_violation")
    assert viol.detail["members"] == [2]
    assert viol.detail["invariants"] == {"2": ["total_heat"]}
    assert kinds.index("member_rollback") < kinds.index(
        "integrity_resolved")
    assert res.quarantined == [] and res.retries == {2: 1}
    for m in range(4):
        assert np.array_equal(np.asarray(res.state["T"][m]),
                              np.asarray(clean.state["T"][m])), m


# ---------------------------------------------------------------------------
# (iv) the registry hook + statusd readiness
# ---------------------------------------------------------------------------

def test_registry_and_auto_match():
    _grid()
    grid = igg.get_global_grid()
    # The built-in families registered at import.
    from igg.models import diffusion3d, shallow_water, wave2d  # noqa: F401

    fams = integ.registered_families()
    assert {"diffusion3d", "shallow_water", "wave2d"} <= set(fams)
    got = integ.match_invariants({"T", "Cp"}, grid)
    assert [i.name for i in got] == ["total_heat"]
    got = integ.match_invariants({"h", "hu", "hv"}, grid)
    assert [i.name for i in got] == ["total_mass"]
    # wave energy is a bounded invariant, periodicity-free.
    got = integ.match_invariants({"P", "Vx", "Vy"}, grid)
    assert [(i.name, i.kind) for i in got] == [("wave_energy", "bounded")]
    igg.finalize_global_grid()
    # Conserved invariants drop off open grids; bounded ones survive.
    _grid(periodx=0)
    grid = igg.get_global_grid()
    assert integ.match_invariants({"T"}, grid) == ()
    assert [i.name for i in integ.match_invariants({"P", "Vx", "Vy"},
                                                   grid)] \
        == ["wave_energy"]


def test_stencil_spec_invariants_register_on_compile():
    from igg.stencil import shallow_water_spec

    spec = shallow_water_spec()
    assert [i.name for i in spec.invariants] == ["total_mass"]
    with pytest.raises(igg.GridError, match="not all declared"):
        from igg.stencil import Field, Update
        from igg.stencil.spec import StencilSpec

        f = Field("a", stagger=(0, 0))
        StencilSpec("bad", fields=[f], updates=[Update(f, f + 1.0)],
                    invariants=(integ.Invariant("x", ("zz",)),))


def test_statusd_readiness_pinned_reason_and_recovery():
    """The pinned /healthz reason: a live integrity_violation flips
    readiness false naming "integrity_violation"; the verified
    rollback's integrity_resolved record recovers it.  /status carries
    the integrity section."""
    from igg.statusd import REASON_INTEGRITY, HealthState

    assert REASON_INTEGRITY == "integrity_violation"
    hs = HealthState(max_fetch_lag=0).attach()
    try:
        tel.emit("integrity_violation", step=30, run="resilient",
                 source="invariant", invariant="total_heat", rank=3,
                 device="cpu:3")
        ready, reasons = hs.readiness()
        assert ready is False
        assert reasons[0]["reason"] == REASON_INTEGRITY
        assert reasons[0]["rank"] == 3
        view = hs.view()
        assert view["integrity"]["violation"]["invariant"] == "total_heat"
        assert view["integrity"]["violations_total"] == 1
        tel.emit("integrity_resolved", step=20, run="resilient",
                 from_step=30)
        ready, reasons = hs.readiness()
        assert ready is True and reasons == []
        assert hs.view()["integrity"]["violation"] is None
        assert hs.view()["integrity"]["resolved"]["step"] == 20
    finally:
        hs.detach()


def test_top_renders_integrity_section():
    from igg import top as itop

    status = {"health": {"ready": False,
                         "reasons": [{"reason": "integrity_violation"}]},
              "runs": {}, "integrity": {
                  "violation": {"source": "invariant",
                                "invariant": "total_heat",
                                "rank": 3, "device": "cpu:3", "step": 30},
                  "violations_total": 1}}
    frame = itop.render(status, [])
    assert "NOT READY (integrity_violation)" in frame
    assert "VIOLATION LIVE" in frame and "total_heat" in frame
    status["integrity"] = {"violation": None, "violations_total": 2,
                           "resolved": {"step": 20}}
    frame = itop.render(status, [])
    assert "2 violation(s), last resolved @ step 20" in frame
