"""Coordinate-tool tests: ports the golden tables of
`/root/reference/test/test_tools.jl` (indices shifted to 0-based)."""

import dataclasses

import numpy as np
import pytest

import igg
from igg import shared


def seq(fn, n, d, A, coords=None):
    return [fn(i, d, A, coords) for i in range(n)]


class TestGFunctions:
    """`/root/reference/test/test_tools.jl:15-66` (1-device grid, periodz)."""

    def setup_method(self, _):
        if igg.grid_is_initialized():
            igg.finalize_global_grid()
        self.nx, self.ny, self.nz = 5, 5, 5
        igg.init_global_grid(self.nx, self.ny, self.nz, dimx=1, dimy=1,
                             dimz=1, periodz=1, quiet=True)
        self.P = np.zeros((5, 5, 5))
        self.Vx = np.zeros((6, 5, 5))
        self.Vz = np.zeros((5, 5, 6))
        self.A = np.zeros((5, 5, 7))
        self.Sxz = np.zeros((3, 4, 3))

    def test_n_g(self):
        assert igg.nx_g() == 5
        assert igg.ny_g() == 5
        assert igg.nz_g() == 3
        assert igg.nx_g(self.Vx) == 6
        assert igg.nz_g(self.Vz) == 4
        assert igg.nz_g(self.A) == 5
        assert igg.nx_g(self.Sxz) == 3

    def test_xyz_g(self):
        dx = 8 / (igg.nx_g() - 1)
        dy = 8 / (igg.ny_g() - 1)
        dz = 8 / (igg.nz_g() - 1)
        assert seq(igg.x_g, 5, dx, self.P) == [0, 2, 4, 6, 8]
        assert seq(igg.y_g, 5, dy, self.P) == [0, 2, 4, 6, 8]
        assert seq(igg.z_g, 5, dz, self.P) == [8, 0, 4, 8, 0]
        assert seq(igg.x_g, 6, dx, self.Vx) == [-1, 1, 3, 5, 7, 9]
        assert seq(igg.y_g, 5, dy, self.Vx) == [0, 2, 4, 6, 8]
        assert seq(igg.z_g, 5, dz, self.Vx) == [8, 0, 4, 8, 0]
        assert seq(igg.x_g, 5, dx, self.Vz) == [0, 2, 4, 6, 8]
        assert seq(igg.z_g, 6, dz, self.Vz) == [6, 10, 2, 6, 10, 2]
        assert seq(igg.z_g, 7, dz, self.A) == [4, 8, 0, 4, 8, 0, 4]
        assert seq(igg.x_g, 3, dx, self.Sxz) == [2, 4, 6]
        assert seq(igg.y_g, 4, dy, self.Sxz) == [1, 3, 5, 7]
        assert seq(igg.z_g, 3, dz, self.Sxz) == [0, 4, 8]

    def test_field_forms_match_scalars(self):
        dz = 8 / (igg.nz_g() - 1)
        Vz = igg.zeros((5, 5, 6))
        zf = np.array(igg.z_g_field(dz, Vz))
        assert zf.tolist() == [6, 10, 2, 6, 10, 2]


class TestGFunctionsNonDefaultOverlap:
    """`/root/reference/test/test_tools.jl:68-114` (overlapx=3, overlapz=3)."""

    def setup_method(self, _):
        if igg.grid_is_initialized():
            igg.finalize_global_grid()
        igg.init_global_grid(5, 5, 8, dimx=1, dimy=1, dimz=1, periodz=1,
                             overlapx=3, overlapz=3, quiet=True)

    def test_n_g(self):
        assert igg.nx_g() == 5
        assert igg.ny_g() == 5
        assert igg.nz_g() == 5

    def test_xyz_g(self):
        dx = 8 / (igg.nx_g() - 1)
        dy = 8 / (igg.ny_g() - 1)
        dz = 8 / (igg.nz_g() - 1)
        P = np.zeros((5, 5, 8))
        Vz = np.zeros((5, 5, 9))
        A = np.zeros((5, 5, 10))
        Sxz = np.zeros((3, 4, 6))
        assert seq(igg.x_g, 5, dx, P) == [0, 2, 4, 6, 8]
        assert seq(igg.z_g, 8, dz, P) == [8, 0, 2, 4, 6, 8, 0, 2]
        assert seq(igg.z_g, 9, dz, Vz) == [7, 9, 1, 3, 5, 7, 9, 1, 3]
        assert seq(igg.z_g, 10, dz, A) == [6, 8, 0, 2, 4, 6, 8, 0, 2, 4]
        assert seq(igg.z_g, 6, dz, Sxz) == [0, 2, 4, 6, 8, 0]


class TestSimulatedTopology:
    """`/root/reference/test/test_tools.jl:116-166`: a 3x3x3 grid simulated on
    one device by swapping in modified grid state (here: an immutable replace
    + explicit coords, instead of mutating the struct's vectors)."""

    def setup_method(self, _):
        if igg.grid_is_initialized():
            igg.finalize_global_grid()
        igg.init_global_grid(5, 5, 5, dimx=1, dimy=1, dimz=1, periodz=1,
                             quiet=True)
        g = igg.get_global_grid()
        dims = (3, 3, 3)
        nxyz_g = tuple(
            dims[d] * (g.nxyz[d] - g.overlaps[d])
            + g.overlaps[d] * (g.periods[d] == 0) for d in range(3))
        shared.set_global_grid(dataclasses.replace(g, dims=dims,
                                                   nxyz_g=nxyz_g,
                                                   nprocs=27))
        self.P = np.zeros((5, 5, 5))
        self.A = np.zeros((6, 3, 7))

    def test_n_g(self):
        assert igg.nx_g() == 11 and igg.ny_g() == 11 and igg.nz_g() == 9

    def test_xyz_g_per_coords(self):
        dx = 20 / (igg.nx_g() - 1)
        dy = 20 / (igg.ny_g() - 1)
        dz = 16 / (igg.nz_g() - 1)
        P, A = self.P, self.A
        assert seq(igg.x_g, 5, dx, P, (0, 0, 0)) == [0, 2, 4, 6, 8]
        assert seq(igg.x_g, 5, dx, P, (1, 0, 0)) == [6, 8, 10, 12, 14]
        assert seq(igg.x_g, 5, dx, P, (2, 0, 0)) == [12, 14, 16, 18, 20]
        assert seq(igg.y_g, 5, dy, P, (0, 0, 0)) == [0, 2, 4, 6, 8]
        assert seq(igg.y_g, 5, dy, P, (0, 1, 0)) == [6, 8, 10, 12, 14]
        assert seq(igg.y_g, 5, dy, P, (0, 2, 0)) == [12, 14, 16, 18, 20]
        assert seq(igg.z_g, 5, dz, P, (0, 0, 0)) == [16, 0, 2, 4, 6]
        assert seq(igg.z_g, 5, dz, P, (0, 0, 1)) == [4, 6, 8, 10, 12]
        assert seq(igg.z_g, 5, dz, P, (0, 0, 2)) == [10, 12, 14, 16, 0]
        assert seq(igg.x_g, 6, dx, A, (0, 0, 0)) == [-1, 1, 3, 5, 7, 9]
        assert seq(igg.x_g, 6, dx, A, (1, 0, 0)) == [5, 7, 9, 11, 13, 15]
        assert seq(igg.x_g, 6, dx, A, (2, 0, 0)) == [11, 13, 15, 17, 19, 21]
        assert seq(igg.y_g, 3, dy, A, (0, 0, 0)) == [2, 4, 6]
        assert seq(igg.y_g, 3, dy, A, (0, 1, 0)) == [8, 10, 12]
        assert seq(igg.y_g, 3, dy, A, (0, 2, 0)) == [14, 16, 18]
        assert seq(igg.z_g, 7, dz, A, (0, 0, 0)) == [14, 16, 0, 2, 4, 6, 8]
        assert seq(igg.z_g, 7, dz, A, (0, 0, 1)) == [2, 4, 6, 8, 10, 12, 14]
        assert seq(igg.z_g, 7, dz, A, (0, 0, 2)) == [8, 10, 12, 14, 16, 0, 2]


def test_tic_toc():
    igg.init_global_grid(4, 4, 4, quiet=True)
    igg.tic()
    t = igg.toc()
    assert t >= 0.0
    igg.tic()
    assert igg.toc() <= 1.0


def test_coord_fields_broadcast():
    igg.init_global_grid(4, 4, 4, periodx=1, periody=1, periodz=1, quiet=True)
    T = igg.zeros((4, 4, 4))
    X, Y, Z = igg.coord_fields(1.0, 1.0, 1.0, T)
    F = X + Y + Z + 0 * T
    assert F.shape == T.shape
    # spot-check against the scalar form
    g = igg.get_global_grid()
    F_np = np.array(F)
    probe = np.zeros((4, 4, 4))
    for c in [(0, 0, 0), (1, 1, 1), (1, 0, 1)]:
        val = (igg.x_g(2, 1.0, probe, c) + igg.y_g(1, 1.0, probe, c)
               + igg.z_g(3, 1.0, probe, c))
        assert F_np[c[0] * 4 + 2, c[1] * 4 + 1, c[2] * 4 + 3] == pytest.approx(val)


def test_barrier_is_single_scalar_collective():
    """VERDICT round-1 item 10: `barrier()` must stay flat in device count —
    one compiled program reducing ONE scalar token over the mesh plus one
    host read, not a per-device host loop.  Asserted structurally on the
    lowered program: exactly one all-reduce, scalar-shaped."""
    import jax

    import igg
    from igg import tools

    igg.init_global_grid(6, 6, 6, quiet=True)  # 8 devices
    igg.barrier()
    fn = next(iter(tools._barrier_fns.values()))
    import re

    hlo = fn.lower().compile().as_text()
    # sync or async lowering; must be present (the collective exists) and
    # not multiplied into a per-device loop of collectives
    n_allreduce = len(re.findall(r"all-reduce(?:-start)?\(", hlo))
    assert 1 <= n_allreduce <= 2, hlo[:2000]
    assert "f32[]" in hlo                  # scalar token
    # and it is cached: a second call compiles nothing new
    n = len(tools._barrier_fns)
    igg.barrier()
    assert len(tools._barrier_fns) == n
    igg.finalize_global_grid()
