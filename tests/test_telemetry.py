"""The unified observability subsystem (igg/telemetry.py) and its
round-12 satellites: the event bus + flight recorder, the metrics
registry + Prometheus exposition, session JSONL/trace artifacts, the
multihost merge tool, the chaos-proven post-mortem timeline (the
acceptance contract: one failure reconstructed from the artifacts
ALONE), the zero-additional-host-syncs sentinel, `igg.profiling.trace`
hardening, and the `igg.timing.time_steps` slope-method math."""

import json
import pathlib
import time
import warnings

import numpy as np
import pytest

import igg
from igg import telemetry as tel


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Metrics, the flight-recorder ring, and sessions are process-global
    (by design — they outlive grids); isolate every test.  The ring clear
    matters in the full suite: by the time this file runs, hundreds of
    earlier tests have filled the ring to its maxlen, where an append
    evicts instead of growing."""
    tel.reset_metrics()
    tel._ring().clear()
    yield
    for s in list(tel._SESSIONS):
        s.detach()
    tel.reset_metrics()


# ---------------------------------------------------------------------------
# Harness (the test_resilience mini-step: deterministic, 6^3 on the
# (2,2,2) mesh)
# ---------------------------------------------------------------------------

def _grid(**kw):
    args = dict(periodx=1, periody=1, periodz=1, quiet=True)
    args.update(kw)
    igg.init_global_grid(6, 6, 6, **args)


def _make_step():
    from igg.ops import interior_add

    @igg.sharded
    def step(T):
        lap = (T[:-2, 1:-1, 1:-1] + T[2:, 1:-1, 1:-1]
               + T[1:-1, :-2, 1:-1] + T[1:-1, 2:, 1:-1]
               + T[1:-1, 1:-1, :-2] + T[1:-1, 1:-1, 2:]
               - 6.0 * T[1:-1, 1:-1, 1:-1])
        return igg.update_halo_local(interior_add(T, 0.1 * lap))

    return lambda st: {"T": step(st["T"])}


def _init_state(seed=3):
    rng = np.random.default_rng(seed)
    T = igg.from_local_blocks(lambda c, ls: rng.standard_normal(ls),
                              (6, 6, 6))
    return {"T": igg.update_halo(T)}


# ---------------------------------------------------------------------------
# (i) metrics registry
# ---------------------------------------------------------------------------

def test_metrics_registry_types_and_snapshot():
    c = tel.counter("igg_t_total")
    c.inc()
    c.inc(2.5)
    tel.gauge("igg_t_gauge").set(-4.0)
    h = tel.histogram("igg_t_hist")
    for v in (0.5, 1.5, 1.0):
        h.observe(v)
    snap = tel.snapshot()
    assert snap["igg_t_total"] == {"type": "counter", "value": 3.5}
    assert snap["igg_t_gauge"]["value"] == -4.0
    assert snap["igg_t_hist"] == {"type": "histogram", "count": 3,
                                  "sum": 3.0, "min": 0.5, "max": 1.5}
    # Same (name, labels) -> the same instance; labels key distinct series.
    assert tel.counter("igg_t_total") is c
    tel.counter("igg_t_total", tier="a").inc()
    assert tel.snapshot()['igg_t_total{tier="a"}']["value"] == 1.0
    # One name, one type.
    with pytest.raises(igg.GridError, match="one name, one type"):
        tel.gauge("igg_t_total")
    # Counters refuse to go backwards.
    with pytest.raises(igg.GridError, match="negative"):
        c.inc(-1)


def test_prometheus_exposition_format():
    tel.counter("igg_p_total", job="x").inc(2)
    tel.gauge("igg_p_depth").set(7)
    tel.histogram("igg_p_lat").observe(0.25)
    text = tel.prometheus_text()
    assert "# TYPE igg_p_total counter" in text
    assert 'igg_p_total{job="x"} 2.0' in text
    assert "# TYPE igg_p_depth gauge" in text and "igg_p_depth 7.0" in text
    assert "# TYPE igg_p_lat summary" in text
    assert "igg_p_lat_count 1" in text and "igg_p_lat_sum 0.25" in text
    # Every non-comment line is "name{...} value" — parseable exposition.
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        name, value = line.rsplit(" ", 1)
        float(value)
        assert name[0].isalpha()


# ---------------------------------------------------------------------------
# (ii) event bus, flight recorder, sessions
# ---------------------------------------------------------------------------

def test_emit_lands_in_flight_recorder_ring():
    n0 = len(tel.flight_recorder())
    rec = tel.emit("unit_test_event", step=12, foo="bar")
    ring = tel.flight_recorder()
    assert len(ring) == n0 + 1 and ring[-1] is rec
    assert rec.kind == "unit_test_event" and rec.step == 12
    assert rec.payload == {"foo": "bar"}
    assert rec.wall > 0 and rec.t > 0 and rec.process == 0


def test_flight_recorder_dump_and_ring_bound(tmp_path):
    ring_max = tel._ring().maxlen
    for i in range(ring_max + 10):
        tel.emit("flood", step=i)
    assert len(tel.flight_recorder()) == ring_max   # bounded
    out = tel.dump_flight_recorder("unit test", tmp_path / "f.json")
    assert out == [tmp_path / "f.json"]
    doc = json.loads((tmp_path / "f.json").read_text())
    assert doc["reason"] == "unit test"
    assert len(doc["events"]) == ring_max
    assert doc["events"][-1]["kind"] == "flood"


def test_session_writes_jsonl_metrics_and_valid_chrome_trace(tmp_path):
    with tel.Telemetry(tmp_path) as t:
        tel.emit("alpha", step=1, a=1)
        with tel.span("region", step=2, tag="x"):
            time.sleep(0.001)
        tel.counter("igg_s_total").inc()
    lines = [json.loads(l) for l in
             (tmp_path / "events_r0.jsonl").read_text().splitlines()]
    assert [l["kind"] for l in lines] == ["alpha", "span"]
    assert lines[1]["payload"]["name"] == "region"
    assert lines[1]["payload"]["dur_s"] >= 0.001
    snap = json.loads((tmp_path / "metrics_r0.jsonl").read_text()
                      .splitlines()[-1])
    assert snap["metrics"]["igg_s_total"]["value"] == 1.0
    assert "igg_s_total 1.0" in (tmp_path / "metrics_r0.prom").read_text()
    # The span export is VALID Chrome-trace JSON: an object with a
    # traceEvents list of complete ("ph": "X") events carrying numeric
    # ts/dur — what Perfetto/chrome://tracing requires.
    doc = json.loads((tmp_path / "trace_r0.json").read_text())
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    ev = doc["traceEvents"][0]
    assert ev["ph"] == "X" and ev["name"] == "region"
    assert isinstance(ev["ts"], float) and isinstance(ev["dur"], float)
    assert ev["args"]["tag"] == "x"
    assert not t.attached


def test_span_capture_disabled_by_env_knob(monkeypatch):
    monkeypatch.setenv("IGG_TELEMETRY_SPANS", "0")
    n0 = len(tel.flight_recorder())
    with tel.span("invisible"):
        pass
    assert len(tel.flight_recorder()) == n0


def test_as_session_coercions(tmp_path, monkeypatch):
    assert tel.as_session(None) is None
    assert tel.as_session(False) is None
    s = tel.as_session(tmp_path / "x")
    assert isinstance(s, tel.Telemetry) and not s.attached
    assert tel.as_session(s) is s
    with pytest.raises(igg.GridError, match="IGG_TELEMETRY_DIR"):
        tel.as_session(True)
    monkeypatch.setenv("IGG_TELEMETRY_DIR", str(tmp_path / "env"))
    auto = tel.as_session(None)
    assert isinstance(auto, tel.Telemetry)
    assert auto.dir == tmp_path / "env"
    assert tel.as_session(False) is None     # explicit off beats the env
    with pytest.raises(igg.GridError, match="telemetry="):
        tel.as_session(123)


def test_telemetry_env_knobs_registered():
    from igg import _env

    for name in ("IGG_TELEMETRY_DIR", "IGG_TELEMETRY_FLIGHT_RECORDER",
                 "IGG_TELEMETRY_METRICS_EVERY", "IGG_TELEMETRY_SPANS",
                 "IGG_TELEMETRY_DEVICE"):
        assert name in _env._KNOWN, name


# ---------------------------------------------------------------------------
# (iii) the merge tool
# ---------------------------------------------------------------------------

def _fake_stream(path, process, walls, kinds):
    with open(path, "w") as fh:
        for w, k in zip(walls, kinds):
            fh.write(json.dumps({"t": w, "wall": w, "process": process,
                                 "kind": k, "step": None,
                                 "payload": {}}) + "\n")


def test_merge_orders_rank_streams_by_wall(tmp_path):
    _fake_stream(tmp_path / "events_r0.jsonl", 0, [1.0, 3.0, 5.0],
                 ["a0", "b0", "c0"])
    _fake_stream(tmp_path / "events_r1.jsonl", 1, [2.0, 4.0],
                 ["a1", "b1"])
    merged = tel.merge_streams([tmp_path], tmp_path / "merged.jsonl")
    assert [r["kind"] for r in merged] == ["a0", "a1", "b0", "b1", "c0"]
    on_disk = [json.loads(l) for l in
               (tmp_path / "merged.jsonl").read_text().splitlines()]
    assert on_disk == merged
    # A half-written line (killed process) is skipped, not fatal, and
    # accounted in the trailing summary record.
    (tmp_path / "events_r1.jsonl").open("a").write('{"wall": 9')
    merged2 = tel.merge_streams([tmp_path])
    assert merged2[-1]["kind"] == "merge_summary"
    assert merged2[-1]["payload"]["skipped_lines"] == 1
    (tmp_path / "empty").mkdir()
    with pytest.raises(igg.GridError, match="no event files"):
        tel.merge_streams([tmp_path / "empty"])


def test_merge_cli_entry_point(tmp_path):
    """The `python -m igg.telemetry merge` entry point, driven through
    `_main` in-process (the subprocess form of the same invocation is
    exercised end to end by examples/observed_run.py in ci.sh — spawning
    two fresh interpreters here would re-import jax for nothing)."""
    _fake_stream(tmp_path / "events_r0.jsonl", 0, [1.0, 2.0], ["x", "y"])
    rc = tel._main(["merge", str(tmp_path / "m.jsonl"), str(tmp_path)])
    assert rc == 0
    assert len((tmp_path / "m.jsonl").read_text().splitlines()) == 2
    assert tel._main([]) == 2                       # usage
    assert tel._main(["merge", "out"]) == 2         # missing inputs


# ---------------------------------------------------------------------------
# (iv) the acceptance contract: one chaos-injected failure, the full
# timeline from the telemetry artifacts ALONE
# ---------------------------------------------------------------------------

def test_failure_timeline_from_artifacts_alone(tmp_path):
    """NaN-corrupt kernel under run_resilient: the artifacts (events
    JSONL + metrics snapshot + flight dump) alone yield the NaN detection
    step, the rollback target generation, the retry count, and the
    serving-tier change — with `RunResult.events` / `igg.degrade.events()`
    preserved as compatible views."""
    from igg.models import diffusion3d as d3

    igg.init_global_grid(8, 8, 128, periodx=1, periody=1, periodz=1,
                         quiet=True)
    igg.degrade.reset()   # BEFORE the factory: reset clears ladder state
    params = d3.Params()
    T0, Cp = d3.init_fields(params, dtype=np.float32)
    step = d3.make_step(params, donate=False, pallas_interpret=True)
    tdir = tmp_path / "telemetry"
    ckdir = tmp_path / "ring"
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with igg.chaos.kernel_corrupt("diffusion3d.mosaic"):
            res = igg.run_resilient(
                lambda s: {"T": step(s["T"], Cp)}, {"T": T0 + 0}, 30,
                watch_every=10, checkpoint_dir=ckdir, checkpoint_every=10,
                async_checkpoint=False, telemetry=tdir)
    assert res.steps_done == 30

    # -- the timeline, from the JSONL stream alone --
    recs = [json.loads(l) for l in
            (tdir / "events_r0.jsonl").read_text().splitlines()]
    kinds = [r["kind"] for r in recs]
    i_nan = kinds.index("nan_detected")
    i_rb = kinds.index("rollback")
    i_deg = kinds.index("tier_degraded")
    assert i_nan < i_rb < i_deg                      # the story, in order
    nan_step = recs[i_nan]["step"]
    assert nan_step == 10                            # first watch window
    assert recs[i_nan]["payload"]["counts"]["T"] > 0
    rb = recs[i_rb]
    assert rb["payload"]["path"] == str(ckdir / "ckpt_000000000")
    assert rb["payload"]["attempt"] == 1             # the retry count
    deg = recs[i_deg]["payload"]
    assert deg["tier"] == "diffusion3d.mosaic"
    assert deg["reason"] == "nan_recurrence"
    # Timestamps are monotone within the stream and rank-tagged.
    ts = [r["t"] for r in recs]
    assert ts == sorted(ts)
    assert all(r["process"] == 0 for r in recs)
    # run bracket events frame the stream.
    assert kinds[0] == "run_started" and kinds[-1] == "run_finished"

    # -- the metrics snapshot corroborates the counts --
    snap = json.loads((tdir / "metrics_r0.jsonl").read_text()
                      .splitlines()[-1])["metrics"]
    # Two rollbacks: the first burns retry 1; the recurrence takes the
    # demotion rung (no retry burned) and replays from the same target.
    assert snap['igg_rollbacks_total{run="resilient"}']["value"] \
        == float(kinds.count("rollback")) == 2.0
    assert snap['igg_tier_quarantined_total'
                '{tier="diffusion3d.mosaic"}']["value"] == 1.0
    assert snap["igg_checkpoint_bytes_total"]["value"] > 0
    hist = snap['igg_checkpoint_write_seconds{format="sharded"}']
    assert hist["count"] >= 3 and hist["sum"] > 0
    dispatch = [k for k in snap if k.startswith("igg_tier_dispatch_total")]
    assert any('tier="diffusion3d.xla"' in k for k in dispatch)

    # -- compat views preserved: the per-run list still carries the same
    # incidents (without the bus-only step_stats/span/run-bracket noise) --
    run_kinds = [e.kind for e in res.events]
    assert {"nan_detected", "rollback", "tier_degraded"} <= set(run_kinds)
    assert run_kinds.index("nan_detected") \
        < run_kinds.index("tier_degraded")
    assert not {"step_stats", "span", "run_started"} & set(run_kinds)
    assert any(e["kind"] == "tier_degraded"
               for e in igg.degrade.events())
    igg.degrade.reset()


def test_resilience_error_auto_dumps_flight_recorder(tmp_path):
    _grid()
    step_fn = _make_step()
    plan = igg.chaos.ChaosPlan(nan_at=[(3, "T")])
    with pytest.raises(igg.ResilienceError):
        igg.run_resilient(step_fn, _init_state(), 10, watch_every=5,
                          telemetry=tmp_path, chaos=plan)
    dumps = tel.flight_dumps(tmp_path, rank=0)
    assert dumps, list(tmp_path.iterdir())
    dump = json.loads(dumps[0].read_text())
    assert "ResilienceError" in dump["reason"]
    assert any(r["kind"] == "nan_detected" for r in dump["events"])


# ---------------------------------------------------------------------------
# (v) the zero-additional-host-syncs sentinel
# ---------------------------------------------------------------------------

def test_telemetry_adds_zero_host_syncs(tmp_path, monkeypatch):
    """The dispatch-count/sentinel pattern: count every device-array
    materialization the loop performs (`np.asarray` on jax arrays — the
    only fetch primitive `run_resilient` uses) with telemetry OFF and
    with a session attached.  The counts must be IDENTICAL: step stats
    ride the watchdog's existing probe fetches."""
    from igg import resilience as res_mod

    _grid()
    step_fn = _make_step()
    real_asarray = np.asarray
    fetches = []

    def counting_asarray(x, *a, **kw):
        if hasattr(x, "is_ready"):           # a jax.Array — a device fetch
            fetches.append(type(x).__name__)
        return real_asarray(x, *a, **kw)

    def run(telemetry, comm=None, heal=None, serve=False, integrity=False):
        fetches.clear()
        igg.run_resilient(step_fn, _init_state(), 20, watch_every=5,
                          telemetry=telemetry, comm=comm, heal=heal,
                          serve=serve, integrity=integrity,
                          install_sigterm=False)
        return len(fetches)

    monkeypatch.setattr(res_mod, "np", type(np)("np_proxy"))
    for attr in dir(np):
        try:
            setattr(res_mod.np, attr, getattr(np, attr))
        except (AttributeError, TypeError):
            pass
    res_mod.np.asarray = counting_asarray

    bare = run(telemetry=False)
    observed = run(telemetry=tmp_path)
    assert bare > 0                      # the probes ARE being fetched
    assert observed == bare              # ...and telemetry added none
    # Round 13: with the perf ledger enabled too (IGG_PERF_LEDGER set, a
    # session attached), the watchdog-window attribution is host-side
    # ladder bookkeeping riding the SAME fetches — still zero
    # additional device-array materializations.
    monkeypatch.setenv("IGG_PERF_LEDGER",
                       str(tmp_path / "perf" / "ledger.json"))
    with_perf = run(telemetry=tmp_path / "session2")
    assert with_perf == bare
    # Round 14: with COMM observability enabled too — the stall
    # heartbeat watching every probe and a StepDecomposition monitor
    # dispatching its variant probes at the watch cadence — the
    # decomposition is observed entirely through is_ready polling
    # (never materialized), so the device-array fetch counts are STILL
    # identical.
    from igg import comm as icomm

    def compute(T):
        from igg.ops import interior_add

        lap = (T[:-2, 1:-1, 1:-1] + T[2:, 1:-1, 1:-1]
               + T[1:-1, :-2, 1:-1] + T[1:-1, 2:, 1:-1]
               + T[1:-1, 1:-1, :-2] + T[1:-1, 1:-1, 2:]
               - 6.0 * T[1:-1, 1:-1, 1:-1])
        return interior_add(T, 0.1 * lap)

    monkeypatch.setenv("IGG_COMM_STALL_TIMEOUT", "60")
    monitor = icomm.StepDecomposition(compute, (_init_state()["T"],),
                                      reps=2)
    with_comm = run(telemetry=tmp_path / "session3", comm=monitor)
    assert with_comm == bare
    # Round 15: with the HEAL ENGINE enabled too — the detection half is
    # a bus-subscriber callback, the action half a pending-deque check
    # per iteration; with no fault present neither touches a device, so
    # the fetch counts are STILL identical.
    from igg import heal as iheal

    engine = iheal.HealEngine(iheal.HealPolicy(), run="resilient")
    with_heal = run(telemetry=tmp_path / "session4", heal=engine)
    assert with_heal == bare
    assert engine.actions == [] and not engine.has_pending()
    # Round 18: with the STATUSD live endpoint enabled too — the health
    # tracker is a bus-subscriber callback, the HTTP server and the HBM
    # poller (device.memory_stats is a host-side allocator lookup) live
    # entirely on statusd's own threads — the fetch counts are STILL
    # identical, with a scraper hitting the endpoint mid-run.
    import json as _json
    import threading as _threading
    import urllib.request

    from igg import statusd as istatusd

    srv = istatusd.StatusServer(port=0, hbm_every=0.0).start()
    stop_scrape = _threading.Event()

    def scrape():
        while not stop_scrape.wait(0.02):
            try:
                urllib.request.urlopen(srv.url + "/metrics", timeout=2)
                urllib.request.urlopen(srv.url + "/healthz", timeout=2)
            except Exception:
                continue

    scraper = _threading.Thread(target=scrape, daemon=True)
    scraper.start()
    try:
        with_statusd = run(telemetry=tmp_path / "session5", serve=srv)
    finally:
        stop_scrape.set()
        scraper.join(timeout=5)
    assert with_statusd == bare
    body = urllib.request.urlopen(srv.url + "/status", timeout=2).read()
    assert _json.loads(body)["runs"]["resilient"]["finished"] is True
    srv.stop()
    # Round 19: with the INTEGRITY layer enabled too — invariant probes
    # AND shadow re-execution checks at every window (check_every=1).
    # The invariant moment sums and per-rank partials are FUSED into the
    # watchdog probe (one concatenated vector per window), and the
    # shadow truth replay is pure extra dispatch work whose comparison
    # rides the same vector — the device-array fetch counts are STILL
    # identical.
    from igg import integrity as iintegrity

    cfg = iintegrity.IntegrityConfig(
        invariants=[iintegrity.Invariant("probe_sum", ("T",), moment=1,
                                         kind="conserved", tol=1.0)],
        check_every=1)
    with_integrity = run(telemetry=tmp_path / "session6", integrity=cfg)
    assert with_integrity == bare


# ---------------------------------------------------------------------------
# (vi) ensemble + fleet wiring
# ---------------------------------------------------------------------------

def test_ensemble_emits_member_rates_and_unified_events(tmp_path):
    from helpers import ensemble_member_step, ensemble_states

    _grid()
    states = ensemble_states(4)
    res = igg.run_ensemble(ensemble_member_step(), states, 20,
                           watch_every=5, telemetry=tmp_path / "t",
                           install_sigterm=False)
    assert res.steps_done == 20
    recs = [json.loads(l) for l in
            (tmp_path / "t" / "events_r0.jsonl").read_text().splitlines()]
    started = [r for r in recs if r["kind"] == "run_started"]
    assert started and started[0]["payload"]["run"] == "ensemble"
    assert started[0]["payload"]["members"] == 4
    stats = [r for r in recs if r["kind"] == "step_stats"]
    assert stats, [r["kind"] for r in recs]
    assert stats[-1]["payload"]["members_active"] == 4
    assert stats[-1]["payload"]["member_steps_per_s"] == pytest.approx(
        4 * stats[-1]["payload"]["steps_per_s"])
    snap = tel.snapshot()
    assert snap["igg_member_steps_total"]["value"] == 4 * 20


def test_fleet_emits_job_lifecycle_and_queue_depth(tmp_path):
    from helpers import ensemble_member_step, ensemble_states

    jobs = [igg.Job(name="ja", global_interior=(8, 8, 8), members=2,
                    n_steps=4, watch_every=2, checkpoint_every=2,
                    make_states=lambda grid: ensemble_states(2),
                    step_fn=ensemble_member_step())]
    res = igg.run_fleet(jobs, tmp_path / "w", telemetry=tmp_path / "t",
                        install_sigterm=False)
    assert all(o.status == "done" for o in res.jobs.values())
    recs = [json.loads(l) for l in
            (tmp_path / "t" / "events_r0.jsonl").read_text().splitlines()]
    kinds = [r["kind"] for r in recs]
    assert "job_started" in kinds and "job_done" in kinds
    spans = [r for r in recs if r["kind"] == "span"
             and r["payload"]["name"] == "fleet.job"]
    assert len(spans) == len(jobs)
    snap = tel.snapshot()
    assert snap['igg_fleet_jobs_total{status="done"}']["value"] == len(jobs)
    assert snap["igg_fleet_queue_depth"]["value"] == 0.0


# ---------------------------------------------------------------------------
# (vii) satellites: profiling hardening
# ---------------------------------------------------------------------------

def test_profiling_trace_creates_missing_parents_and_rejects_nesting(
        tmp_path):
    _grid()
    deep = tmp_path / "a" / "b" / "c"              # parents do not exist
    T = igg.zeros((6, 6, 6))
    with igg.profiling.trace(str(deep)) as logdir:
        with pytest.raises(igg.GridError, match="do not nest"):
            with igg.profiling.trace(str(tmp_path / "other")):
                pass
        T = igg.update_halo(T)
        assert pathlib.Path(logdir).is_dir()
    # Re-entrancy state cleared: a new trace works after the first closed.
    with igg.profiling.trace(str(tmp_path / "second")):
        pass
    kinds = [r.kind for r in tel.flight_recorder()]
    assert kinds.count("trace_started") >= 2
    assert kinds.count("trace_stopped") >= 2


def test_profiling_trace_cleans_up_on_start_failure(tmp_path, monkeypatch):
    import jax

    def boom(logdir):
        raise RuntimeError("profiler unavailable")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    with pytest.raises(RuntimeError, match="profiler unavailable"):
        with igg.profiling.trace(str(tmp_path / "x")):
            pass
    # The guard is released: the failure did not wedge future traces.
    monkeypatch.undo()
    with igg.profiling.trace(str(tmp_path / "y")):
        pass


# ---------------------------------------------------------------------------
# (viii) satellites: igg.timing.time_steps
# ---------------------------------------------------------------------------

def test_time_steps_slope_cancels_constant_latency():
    """Synthetic constant-latency step: each call costs `c` seconds; the
    slope (T2-T1)/(n2-n1) must recover `c` even though every batch also
    pays the constant sync/readback the slope is designed to cancel."""
    c = 0.003
    calls = []

    def step(x):
        calls.append(1)
        time.sleep(c)
        return x

    state, sec = igg.time_steps(step, (np.float32(1.0),), n1=3, n2=9,
                                warmup=1)
    assert len(calls) == 1 + 3 + 9                  # deterministic count
    # The slope can only overshoot by sleep()'s scheduler overshoot (a
    # loaded CI host), never undershoot below the programmed latency.
    assert 0.8 * c <= sec <= 5 * c
    assert isinstance(state, tuple)


def test_time_steps_validates_batch_sizes():
    step = lambda x: x
    with pytest.raises(ValueError, match="n2 > n1"):
        igg.time_steps(step, (np.float32(0),), n1=5, n2=5)
    with pytest.raises(ValueError, match="n2 > n1"):
        igg.time_steps(step, (np.float32(0),), n1=8, n2=3)


def test_time_steps_single_element_state_normalization():
    """A bare (non-tuple) state is wrapped, and a step returning a single
    array (not a 1-tuple) keeps working — the documented 1-element
    convenience forms."""
    seen = []

    def step(x):
        seen.append(type(x))
        return x + 1

    state, sec = igg.time_steps(step, np.float64(0.0), n1=2, n2=4,
                                warmup=0)
    assert isinstance(state, tuple) and len(state) == 1
    assert state[0] == 2 + 4                        # every call applied
    assert all(t is not tuple for t in seen)        # elements, not tuples
    assert sec >= 0.0
