"""The self-healing control plane (igg/heal.py) and its round-15
satellites: the three chaos-proven detection→action loops (stall →
elastic re-tile, cost-model drift → re-calibration, lagging fleet job →
repack — each healing bit-exactly with zero operator recovery code),
the budget/hysteresis governor (a flapping signal cannot exceed the
action budget; escalation walks action → demote → fail), the
fsync-hardened journal/manifest commits, and ResilienceError naming its
flight-recorder dump paths."""

import json
import os
import time

import numpy as np
import pytest

import igg
from igg import heal as iheal
from igg import telemetry as tel


@pytest.fixture(autouse=True)
def _clean_observability():
    """Metrics, the flight ring, and the perf ledger are process-global;
    isolate every test (the test_comm fixture's pattern)."""
    tel.reset_metrics()
    tel._ring().clear()
    igg.perf.reset()
    yield
    for s in list(tel._SESSIONS):
        s.detach()
    with tel._lock:
        tel._SUBSCRIBERS.clear()
    tel.reset_metrics()
    igg.perf.reset()


def _grid(n=8, **kw):
    args = dict(periodx=1, periody=1, periodz=1, quiet=True)
    args.update(kw)
    igg.init_global_grid(n, n, n, **args)


def _make_step():
    from igg.ops import interior_add

    @igg.sharded
    def step(T):
        lap = (T[:-2, 1:-1, 1:-1] + T[2:, 1:-1, 1:-1]
               + T[1:-1, :-2, 1:-1] + T[1:-1, 2:, 1:-1]
               + T[1:-1, 1:-1, :-2] + T[1:-1, 1:-1, 2:]
               - 6.0 * T[1:-1, 1:-1, 1:-1])
        return igg.update_halo_local(interior_add(T, 0.1 * lap))

    return lambda st: {"T": step(st["T"])}


def _init_state(n=8, seed=3):
    rng = np.random.default_rng(seed)
    T = igg.from_local_blocks(lambda c, ls: rng.standard_normal(ls),
                              (n, n, n))
    return {"T": igg.update_halo(T)}


# ---------------------------------------------------------------------------
# Loop 1: collective stall -> elastic re-tile (chaos, bit-exact)
# ---------------------------------------------------------------------------

def test_stall_heals_by_elastic_retile_bit_exact(tmp_path, monkeypatch):
    """The acceptance path: a chaos collective stall TIED TO ONE DEVICE
    (the sick-chip shape) fires the stall heartbeat; the heal engine
    seals a final generation, fences the chip, re-plans dims over the
    survivors, and resumes elastically — the run completes bit-exactly
    vs an uninterrupted run, with zero operator recovery code (the
    injected fault heals ITSELF once the sick device leaves the grid)."""
    monkeypatch.setenv("IGG_COMM_STALL_TIMEOUT", "0.05")
    nt = 40
    base = _make_step()
    # A wall-clock floor per dispatch so the run reliably outlives the
    # stall heartbeat's deadline on any host (the math is untouched —
    # bit-exactness is unaffected).
    slow = lambda st: (time.sleep(0.004), base(st))[1]

    _grid()
    res = igg.run_resilient(slow, _init_state(), nt, watch_every=2,
                            install_sigterm=False)
    ref = np.asarray(igg.gather_interior(res.state["T"]))
    igg.finalize_global_grid()

    _grid()
    grid = igg.get_global_grid()
    assert grid.dims == (2, 2, 2)
    sick = list(grid.mesh.devices.flat)[-1]   # the engine's default fence
    eng = iheal.HealEngine(iheal.HealPolicy(max_actions=1, cooldown_s=0.0),
                           run="resilient")
    with igg.chaos.collective_stall(device=sick):
        res2 = igg.run_resilient(
            slow, _init_state(), nt, watch_every=2,
            checkpoint_dir=tmp_path / "ring", checkpoint_every=4,
            max_pending_probes=100, heal=eng,
            telemetry=tmp_path / "tel", install_sigterm=False)
    assert res2.steps_done == nt and res2.retries == 0
    kinds = [e.kind for e in res2.events]
    assert "heal_retile" in kinds
    ev = next(e for e in res2.events if e.kind == "heal_retile")
    assert ev.detail["from_dims"] == [2, 2, 2]
    assert ev.detail["devices"] < 8           # the sick chip was fenced
    g2 = igg.get_global_grid()
    assert sick not in list(g2.mesh.devices.flat)
    assert tuple(ev.detail["dims"]) == g2.dims != (2, 2, 2)
    out = np.asarray(igg.gather_interior(res2.state["T"]))
    np.testing.assert_array_equal(out, ref)   # bit-exact heal
    assert [a["action"] for a in eng.actions] == ["retile"]
    # The whole loop is reconstructable from artifacts alone.
    recs = [json.loads(l) for l in
            (tmp_path / "tel" / "events_r0.jsonl").read_text().splitlines()]
    rk = [r["kind"] for r in recs]
    assert rk.index("collective_stall") < rk.index("heal_planned") \
        < rk.index("heal_retile")


def test_straggler_window_inflation_triggers_retile(tmp_path, monkeypatch):
    """The soft half of loop 1: igg.chaos.straggler rate-limits probe
    readiness after a healthy warm-up, measured watchdog windows inflate
    past skew_tol x the run's own baseline, and the engine re-tiles.
    The slowdown is observational (the simulation itself is untouched),
    so the run completes bit-exactly."""
    nt = 200
    base = _make_step()

    def slow_step(st):
        # A wall-clock floor per dispatch: the windows the straggler
        # inflates (and the baseline under them) stay bounded below on a
        # fast host and the run outlives the injected slowdown.
        time.sleep(0.004)
        return base(st)

    _grid(n=6)
    res = igg.run_resilient(slow_step, _init_state(6), nt,
                            watch_every=2, install_sigterm=False)
    ref = np.asarray(igg.gather_interior(res.state["T"]))
    igg.finalize_global_grid()

    _grid(n=6)
    eng = iheal.HealEngine(
        iheal.HealPolicy(max_actions=1, cooldown_s=0.0, sustain=2,
                         skew_tol=3.0, baseline_windows=2,
                         escalation=()),
        run="resilient")
    with igg.chaos.straggler(rank=0, factor=5.0, base_window_s=0.05,
                             after=8):
        res2 = igg.run_resilient(
            slow_step, _init_state(6), nt, watch_every=2,
            checkpoint_dir=tmp_path / "ring", checkpoint_every=4,
            max_pending_probes=300, heal=eng,
            telemetry=tmp_path / "tel", install_sigterm=False)
    assert res2.steps_done == nt
    assert [a["action"] for a in eng.actions] == ["retile"]
    ev = next(e for e in res2.events if e.kind == "heal_retile")
    assert ev.detail["reason"] == "window_inflation"
    np.testing.assert_array_equal(
        np.asarray(igg.gather_interior(res2.state["T"])), ref)


def test_retile_without_ring_is_skipped_not_fatal(tmp_path, monkeypatch):
    """A retile plan with no checkpoint ring has nothing to seal or
    resume from: the action is skipped with a `heal_skipped` record, the
    run finishes untouched."""
    monkeypatch.setenv("IGG_COMM_STALL_TIMEOUT", "0.05")
    _grid()
    eng = iheal.HealEngine(iheal.HealPolicy(max_actions=1, cooldown_s=0.0),
                           run="resilient")
    base = _make_step()
    slow = lambda st: (time.sleep(0.006), base(st))[1]   # outlive the stall
    with igg.chaos.collective_stall():
        res = igg.run_resilient(slow, _init_state(), 30,
                                watch_every=2, max_pending_probes=100,
                                heal=eng, telemetry=tmp_path,
                                install_sigterm=False)
    assert res.steps_done == 30
    assert igg.get_global_grid().dims == (2, 2, 2)   # untouched
    recs = [json.loads(l) for l in
            (tmp_path / "events_r0.jsonl").read_text().splitlines()]
    skips = [r for r in recs if r["kind"] == "heal_skipped"]
    assert skips and "ring" in skips[0]["payload"]["why"]
    # A skip refunds the budget and never walks the escalation ladder —
    # the run completed, no tier was demoted, nothing was raised.
    assert eng.actions == []
    assert [s["action"] for s in eng.skipped] == ["retile"]
    assert not any(r["kind"] == "heal_escalated" for r in recs)


# ---------------------------------------------------------------------------
# Loop 2: cost-model drift -> re-calibration (chaos, bit-exact)
# ---------------------------------------------------------------------------

def test_drift_recalibrates_and_heals_bit_exact(tmp_path):
    """A stale calibration (10 s/step vs sub-ms reality) fires
    cost_model_drift on the first watchdog-window sample; the engine
    invalidates the family's ledger entries, re-measures, re-registers
    the prediction, and emits `recalibrated` — once (repeats are
    advisory noise, suppressed).  The run's physics is untouched:
    bit-exact vs a clean run."""
    from igg.models import diffusion3d as d3

    def run(**kw):
        igg.init_global_grid(16, 16, 16, periodx=1, periody=1, periodz=1,
                             quiet=True)
        params = d3.Params()
        T0, Cp = d3.init_fields(params, dtype=np.float32)
        step = d3.make_step(params, donate=False)
        res = igg.run_resilient(
            lambda s: {"T": step(s["T"], s["Cp"]), "Cp": s["Cp"]},
            {"T": T0, "Cp": Cp}, 40, watch_every=5,
            install_sigterm=False, **kw)
        out = np.asarray(igg.gather_interior(res.state["T"]))
        igg.finalize_global_grid()
        return res, out

    _, ref = run()

    eng = iheal.HealEngine(iheal.HealPolicy(max_actions=3, cooldown_s=0.0),
                           run="resilient")
    with igg.chaos.stale_calibration("diffusion3d", 10.0):
        res, out = run(heal=eng, telemetry=tmp_path)
    np.testing.assert_array_equal(out, ref)
    recals = [a for a in eng.actions if a["action"] == "recalibrate"]
    assert len(recals) == 1 and recals[0]["family"] == "diffusion3d"
    # The re-registered prediction is the measurement, not the lie.
    with igg.perf._lock:
        pred = dict(igg.perf._PREDICTIONS["diffusion3d"])
    assert pred["source"] == "heal" and pred["s_per_step"] < 1.0
    # The whole loop from artifacts alone: drift -> planned ->
    # invalidated -> recalibrated, in order.
    recs = [json.loads(l) for l in
            (tmp_path / "events_r0.jsonl").read_text().splitlines()]
    rk = [r["kind"] for r in recs]
    assert rk.index("cost_model_drift") < rk.index("heal_planned") \
        < rk.index("perf_invalidated") < rk.index("recalibrated")
    recal = next(r for r in recs if r["kind"] == "recalibrated")
    assert recal["payload"]["family"] == "diffusion3d"
    assert recal["payload"]["invalidated"] >= 1
    assert recal["payload"]["measured_s_per_step"] < 1.0


def test_recalibrate_unknown_family_reanchors_from_ledger():
    """Families igg.perf.calibrate cannot build re-anchor to the
    freshest measured sample: the measurement IS the truth."""
    _grid()
    igg.perf.record("myphysics", "myphysics.xla", 2.5, source="watchdog",
                    local_shape=(8, 8, 8), dtype="float32",
                    dims=(2, 2, 2), backend="cpu", device_kind="cpu")
    igg.perf.predict("myphysics", 99.0)
    sec = iheal.recalibrate("myphysics")
    assert sec == pytest.approx(2.5e-3)
    with igg.perf._lock:
        assert igg.perf._PREDICTIONS["myphysics"]["s_per_step"] == \
            pytest.approx(2.5e-3)
    # The ledger was re-seeded with the anchor sample.
    e = igg.perf.best("myphysics")
    assert e is not None and e["best_ms"] == pytest.approx(2.5)
    # With no measurement at all there is nothing to anchor to: None.
    assert iheal.recalibrate("neverseen") is None


def test_perf_invalidate_drops_entries_and_rearms_drift():
    igg.perf.record("famA", "famA.xla", 1.0)
    igg.perf.record("famA", "famA.mosaic", 0.5)
    igg.perf.record("famB", "famB.xla", 2.0)
    with igg.perf._lock:
        igg.perf._DRIFT_EMITTED.add(("famA", "famA.xla"))
        igg.perf._DRIFT_EMITTED.add(("famB", "famB.xla"))
    assert igg.perf.invalidate("famA", tier="famA.mosaic") == 1
    assert [e["tier"] for e in igg.perf.query("famA")] == ["famA.xla"]
    assert igg.perf.invalidate("famA") == 1
    assert igg.perf.query("famA") == []
    assert igg.perf.query("famB") != []
    with igg.perf._lock:
        assert ("famA", "famA.xla") not in igg.perf._DRIFT_EMITTED
        assert ("famB", "famB.xla") in igg.perf._DRIFT_EMITTED
    assert any(r.kind == "perf_invalidated"
               for r in tel.flight_recorder())


# ---------------------------------------------------------------------------
# Loop 3: lagging fleet job -> repack (chaos, bit-exact)
# ---------------------------------------------------------------------------

def test_lagging_job_repacks_bit_exact(tmp_path, monkeypatch):
    """A fleet job whose measured member rate collapses below its
    cost-model expectation (igg.chaos.throughput_collapse — a rate
    limit on the probe-readiness channel, the simulation untouched) is
    preempted at the next generation and re-admitted at a DIFFERENT
    member packing (grid -> batch here), resuming elastically from its
    ring — final member states bit-identical to an uninterrupted
    drain."""
    from test_fleet import _job

    monkeypatch.setenv("IGG_ENSEMBLE_MAX_PENDING_PROBES", "1000")
    caps = {}

    def capture(tag):
        import igg.ensemble as ens

        orig = ens.run_ensemble

        def wrapper(*a, **kw):
            res = orig(*a, **kw)
            if not res.preempted:
                caps[tag] = np.stack(
                    [np.asarray(igg.gather_interior(res.state["T"][m]))
                     for m in range(res.members)])
            return res
        return wrapper

    import igg.ensemble as ens

    # 600 steps at >= one collective dispatch each: even on a fast host
    # the job's wall time spans several 0.02 s readiness grants, so the
    # collapsed windows (2 steps / 0.02 s x 8 members = 800 member-
    # steps/s << 0.5 x 5000) are measured BEFORE the job can finish.
    kw = dict(seed=5, members=8, n_steps=600, packing="grid",
              watch_every=2, checkpoint_every=20)
    monkeypatch.setattr(ens, "run_ensemble", capture("clean"))
    ref = igg.run_fleet([_job("j", **kw)], tmp_path / "clean")
    assert ref.jobs["j"].status == "done"

    monkeypatch.setattr(ens, "run_ensemble", capture("healed"))
    eng = iheal.HealEngine(
        iheal.HealPolicy(max_actions=1, cooldown_s=0.0, sustain=2),
        run="fleet")
    job = _job("j", expected_member_steps_per_s=5000.0, **kw)
    with igg.chaos.throughput_collapse("j", delay_s=0.02):
        res = igg.run_fleet([job], tmp_path / "healed", heal=eng)
    o = res.jobs["j"]
    assert o.status == "done" and not res.preempted
    repack = next(e for e in o.events if e.kind == "heal_repack")
    assert repack.detail["from_packing"] == "grid"
    assert repack.detail["packing"] == "batch"
    assert o.result.packing == "batch"
    assert [a["action"] for a in eng.actions] == ["repack"]
    np.testing.assert_array_equal(caps["healed"], caps["clean"])
    # The journal saw the heal preemption and the final completion.
    j = json.loads((tmp_path / "healed" / "journal.json").read_text())
    assert j["jobs"]["j"]["status"] == "done"
    assert j["jobs"]["j"]["attempts"] == 2     # launch + re-admission


def test_repack_choice_flips_and_falls_back():
    from igg.fleet import _repack_choice

    job = igg.Job(name="x", global_interior=(8, 8, 8), members=8,
                  n_steps=1, make_states=lambda g: [], step_fn=lambda s: s)
    devs = list(range(8))
    # grid -> batch when the interior fits one device and M % ndev == 0.
    assert _repack_choice(job, "grid", devs) == ("batch", devs)
    # batch -> grid always.
    assert _repack_choice(job, "batch", devs) == ("grid", devs)
    # No legal flip (members not divisible): halve the pool instead.
    job_odd = igg.Job(name="y", global_interior=(8, 8, 8), members=3,
                      n_steps=1, make_states=lambda g: [],
                      step_fn=lambda s: s)
    packing, pool = _repack_choice(job_odd, "grid", devs)
    assert packing == "grid" and len(pool) == 4


# ---------------------------------------------------------------------------
# The budget/hysteresis governor
# ---------------------------------------------------------------------------

def test_flapping_signal_cannot_exceed_action_budget():
    """The acceptance hysteresis test: a signal flapping 30x plans at
    most `max_actions` actions (escalation disabled); every other
    decision is an accounted suppression."""
    eng = iheal.HealEngine(
        iheal.HealPolicy(max_actions=2, cooldown_s=0.0, sustain=1,
                         escalation=()), run="resilient")
    eng.attach()
    executed = 0
    for i in range(30):
        tel.emit("collective_stall", step=i, run="resilient",
                 in_flight="probe")
        act = eng.pop()
        if act is not None:
            eng.record_done(act["action"])
            executed += 1
    eng.detach()
    assert executed == 2
    assert eng.suppressed == 28
    kinds = [r.kind for r in tel.flight_recorder()]
    assert kinds.count("heal_planned") == 2
    assert "heal_suppressed" in kinds
    assert "heal_escalated" not in kinds


def test_cooldown_and_sustain_hysteresis():
    eng = iheal.HealEngine(
        iheal.HealPolicy(max_actions=10, cooldown_s=3600.0, sustain=3),
        run="resilient")
    eng.attach()
    # A soft signal below `sustain` consecutive observations never acts,
    # and a healthy window in between RESETS the counter.
    for ms in (10.0, 10.0, 10.0, 50.0, 50.0, 10.0, 50.0, 50.0):
        tel.emit("step_stats", run="resilient", ms_per_step=ms,
                 steps_per_s=1e3 / ms, window_steps=2)
    assert not eng.has_pending()
    # The third consecutive excess crosses sustain -> one action.
    for _ in range(3):
        tel.emit("step_stats", run="resilient", ms_per_step=50.0,
                 steps_per_s=20.0, window_steps=2)
    assert eng.has_pending()
    act = eng.pop()
    assert act["action"] == "retile"
    eng.record_done("retile")
    # Cooldown: an immediate re-signal is suppressed, not planned.
    for _ in range(3):
        tel.emit("step_stats", run="resilient", ms_per_step=50.0,
                 steps_per_s=20.0, window_steps=2)
    assert not eng.has_pending() and eng.suppressed >= 1
    eng.detach()


def test_escalation_walks_demote_then_fail(tmp_path):
    """Budget exhausted + persistent signal: the ladder walks demote
    (quarantine the serving tiers) then fail (HealEscalation — a
    ResilienceError carrying the flight-dump paths in its message).
    Signals are injected directly onto the bus (the StallWatchdog's
    once-per-episode debounce is pinned separately in test_comm)."""
    _grid()
    eng = iheal.HealEngine(
        iheal.HealPolicy(max_actions=0, cooldown_s=0.0,
                         escalation=("demote", "fail")), run="resilient")
    eng.attach()
    tel.emit("collective_stall", step=1, run="resilient",
             in_flight="probe")                 # budget 0 -> demote planned
    tel.emit("collective_stall", step=2, run="resilient",
             in_flight="probe")                 # ladder walks on -> fail
    with pytest.raises(iheal.HealEscalation) as ei:
        igg.run_resilient(_make_step(), _init_state(), 20, watch_every=5,
                          heal=eng, telemetry=tmp_path,
                          install_sigterm=False)
    err = ei.value
    assert [a["action"] for a in eng.actions] == ["demote"]
    assert err.dump_paths and "flight recorder dumped to" in str(err)
    assert all(p.exists() for p in err.dump_paths)
    assert isinstance(err, igg.ResilienceError)
    kinds = [r.kind for r in tel.flight_recorder()]
    assert kinds.count("heal_escalated") == 2


def test_policy_validation_and_as_engine_coercion(monkeypatch):
    with pytest.raises(igg.GridError, match="sustain"):
        iheal.HealPolicy(sustain=0)
    with pytest.raises(igg.GridError, match="escalation"):
        iheal.HealPolicy(escalation=("explode",))
    assert iheal.as_engine(False) is None
    assert iheal.as_engine(None) is None          # IGG_HEAL unset: off
    monkeypatch.setenv("IGG_HEAL", "1")
    eng = iheal.as_engine(None, run="fleet")
    assert isinstance(eng, iheal.HealEngine) and eng.run == "fleet"
    monkeypatch.setenv("IGG_HEAL_MAX_ACTIONS", "7")
    assert iheal.as_engine(True).policy.max_actions == 7
    pol = iheal.HealPolicy(max_actions=1)
    assert iheal.as_engine(pol).policy is pol
    eng2 = iheal.HealEngine(pol)
    assert iheal.as_engine(eng2) is eng2
    with pytest.raises(igg.GridError, match="heal="):
        iheal.as_engine("bogus")


def test_heal_env_knobs_registered():
    from igg import _env

    for name in ("IGG_HEAL", "IGG_HEAL_MAX_ACTIONS", "IGG_HEAL_COOLDOWN",
                 "IGG_HEAL_SKEW_TOL", "IGG_HEAL_THROUGHPUT_TOL",
                 "IGG_HEAL_SUSTAIN"):
        assert name in _env._KNOWN, name


def test_rank_skew_record_feeds_retile_signal():
    """The multi-rank straggler feed: a rank_skew bus record (emitted by
    igg.comm.rank_skew) beyond skew_tol plans a retile."""
    eng = iheal.HealEngine(
        iheal.HealPolicy(max_actions=1, cooldown_s=0.0, sustain=1,
                         skew_tol=2.0), run="resilient")
    eng.attach()
    tel.emit("rank_skew", step=50, max_skew_ms=30.0, median_ms=10.0,
             worst_rank=3, ranks=4)
    act = eng.pop()
    eng.detach()
    assert act is not None and act["action"] == "retile"
    assert act["reason"] == "rank_skew_excess"


# ---------------------------------------------------------------------------
# Satellites: fsync'd commit records, dump-path-carrying errors
# ---------------------------------------------------------------------------

def test_journal_and_manifest_seal_are_fsynced(tmp_path, monkeypatch):
    """The power-cut hardening: the fleet journal write and the sharded
    generation's manifest seal fsync the tmp file before the atomic
    rename (and the directory after)."""
    synced = []
    real_fsync = os.fsync
    monkeypatch.setattr(os, "fsync",
                        lambda fd: (synced.append(fd), real_fsync(fd))[1])

    from igg.fleet import _write_journal

    _write_journal(tmp_path / "journal.json",
                   {"format": "igg-fleet-journal-v1", "jobs": {}})
    assert len(synced) >= 1          # tmp file (+ directory where supported)

    synced.clear()
    _grid()
    T = igg.zeros((8, 8, 8)) + 1.0
    igg.save_checkpoint_sharded(tmp_path / "gen_000000001", T=T)
    assert len(synced) >= 1          # the manifest seal
    # And the generation still reads back healthy.
    assert igg.verify_checkpoint(tmp_path / "gen_000000001")


def test_resilience_error_names_its_dump_paths(tmp_path):
    """Satellite: the exhaustion path's ResilienceError carries the
    flight-recorder dump path(s) written during auto-dump, named in the
    message."""
    _grid()
    plan = igg.chaos.ChaosPlan(nan_at=[(3, "T")])
    with pytest.raises(igg.ResilienceError) as ei:
        igg.run_resilient(_make_step(), _init_state(), 10, watch_every=5,
                          telemetry=tmp_path, chaos=plan,
                          install_sigterm=False)
    err = ei.value
    # Dumps are run-id-suffixed (round 18): resolve through the glob
    # helper rather than pinning a filename.
    dumps = tel.flight_dumps(tmp_path, rank=0)
    assert len(dumps) == 1 and err.dump_paths == dumps
    assert str(dumps[0]) in str(err)
    # Without a sink there is nothing to name — no paths, clean message.
    igg.finalize_global_grid()
    _grid()
    plan.reset()
    with pytest.raises(igg.ResilienceError) as ei2:
        igg.run_resilient(_make_step(), _init_state(), 10, watch_every=5,
                          chaos=plan, install_sigterm=False)
    assert ei2.value.dump_paths == []
    assert "flight recorder" not in str(ei2.value)
