"""Halo-exchange tests.

Ports the end-to-end coverage of `/root/reference/test/test_update_halo.jl`
(§1 argument checks, §4 end-to-end updates) onto the 8-device CPU mesh: the
coordinate-encoded oracle transfers verbatim (see tests/helpers.py); the
multi-device mesh exercises the very shard_map/ppermute program that runs on
a TPU slice, while `dimx=dimy=dimz=1` cases exercise the self-wrap (periodic,
single-device) path, the analog of the reference's self-neighbor branch.
"""

import numpy as np
import pytest

import igg
from igg import halo

from helpers import roundtrip


# ---------------------------------------------------------------------------
# §1 argument checks (`/root/reference/test/test_update_halo.jl:38-55`)
# ---------------------------------------------------------------------------

class TestArgumentChecks:
    def test_no_halo_field_rejected(self):
        igg.init_global_grid(8, 8, 8, quiet=True)
        A = igg.zeros((8, 8, 8))
        B = igg.zeros((7, 6, 6))  # ol = 2 + (7-8) = 1 < 2 in every dim
        with pytest.raises(igg.GridError, match="position 1 has no halo"):
            igg.update_halo(A, B)
        with pytest.raises(igg.GridError, match="has no halo"):
            igg.update_halo(B)

    def test_duplicate_field_rejected(self):
        igg.init_global_grid(8, 8, 8, quiet=True)
        A = igg.zeros((8, 8, 8))
        with pytest.raises(igg.GridError, match="duplicate"):
            igg.update_halo(A, A)

    def test_mixed_dtype_rejected(self):
        igg.init_global_grid(8, 8, 8, quiet=True)
        A = igg.zeros((8, 8, 8), dtype=np.float32)
        B = igg.zeros((8, 8, 8), dtype=np.float64)
        with pytest.raises(igg.GridError, match="different type"):
            igg.update_halo(A, B)

    def test_uninitialized_rejected(self):
        with pytest.raises(igg.GridError, match="init_global_grid"):
            igg.update_halo(np.zeros((4, 4, 4)))


# ---------------------------------------------------------------------------
# §4 end-to-end halo updates (`/root/reference/test/test_update_halo.jl:655-963`)
# ---------------------------------------------------------------------------

PERIODIC = dict(periodx=1, periody=1, periodz=1)


class TestEndToEnd3D:
    def test_periodic_multidevice(self):
        igg.init_global_grid(6, 6, 6, **PERIODIC, quiet=True)  # dims (2,2,2)
        out, exp = roundtrip((6, 6, 6))
        np.testing.assert_array_equal(out, exp)

    def test_periodic_single_device_selfwrap(self):
        igg.init_global_grid(6, 6, 6, dimx=1, dimy=1, dimz=1, **PERIODIC,
                             quiet=True)
        out, exp = roundtrip((6, 6, 6))
        np.testing.assert_array_equal(out, exp)

    def test_open_boundaries(self):
        igg.init_global_grid(6, 6, 6, quiet=True)  # dims (2,2,2), all open
        out, exp = roundtrip((6, 6, 6))
        np.testing.assert_array_equal(out, exp)

    def test_mixed_periodicity(self):
        igg.init_global_grid(6, 6, 6, periody=1, quiet=True)
        out, exp = roundtrip((6, 6, 6))
        np.testing.assert_array_equal(out, exp)

    def test_staggered_arrays(self):
        from helpers import assert_halo_agreement

        igg.init_global_grid(6, 6, 6, **PERIODIC, quiet=True)
        for lshape in [(7, 6, 6), (6, 7, 6), (6, 6, 7)]:  # Vx, Vy, Vz
            out, exp = roundtrip(lshape)
            np.testing.assert_array_equal(out, exp)
            # The post-exchange invariant the degrade verify guard leans
            # on: every overlap cell equals the owning neighbor's interior.
            assert_halo_agreement(out, lshape)

    def test_larger_overlap(self):
        igg.init_global_grid(8, 8, 8, overlapx=3, overlapz=4, **PERIODIC,
                             quiet=True)
        out, exp = roundtrip((8, 8, 8))
        np.testing.assert_array_equal(out, exp)

    def test_no_halo_dimension_untouched(self):
        # qx-like staggered field: ol=1 in y/z -> those dims are skipped.
        igg.init_global_grid(6, 6, 6, **PERIODIC, quiet=True)
        out, exp = roundtrip((6, 5, 5))
        np.testing.assert_array_equal(out, exp)

    def test_interior_never_modified(self):
        igg.init_global_grid(6, 6, 6, quiet=True)
        from helpers import encoded_field
        import jax
        field = encoded_field((6, 6, 6))
        before = np.array(field)
        out = np.array(igg.update_halo(jax.device_put(
            before, igg.sharding_for(3))))
        # with no zeroed halos and consistent encoding, nothing changes at all
        np.testing.assert_array_equal(out, before)


class TestSequentialEquivalence:
    """The engine's plane-level exchange + one-pass assembly must equal the
    direct transcription of the reference's sequential in-place update
    (`/root/reference/src/update_halo.jl:36-74`) on *random* data — the
    coordinate-encoded oracle cannot see corner/edge mistakes at open
    boundaries because it zeroes every halo (stale == received == 0 there)."""

    @staticmethod
    def _sequential_oracle(A, grid):
        from jax import lax
        for d in range(min(A.ndim, igg.NDIMS)):
            ol = grid.ol_of_local(d, A.shape)
            if ol < 2:
                continue
            s = A.shape[d]
            ls = lax.slice_in_dim(A, ol - 1, ol, axis=d)
            rs = lax.slice_in_dim(A, s - ol, s - ol + 1, axis=d)
            nf, nl = halo.exchange_planes(
                ls, rs, lax.slice_in_dim(A, 0, 1, axis=d),
                lax.slice_in_dim(A, s - 1, s, axis=d),
                d, grid.dims[d], bool(grid.periods[d]))
            A = lax.dynamic_update_slice_in_dim(A, nl, s - 1, axis=d)
            A = lax.dynamic_update_slice_in_dim(A, nf, 0, axis=d)
        return A

    def _check(self, lshape):
        import jax

        grid = igg.get_global_grid()
        rng = np.random.default_rng(42)
        field = igg.from_local_blocks(
            lambda coords, ls: rng.standard_normal(ls) + 100.0 * coords[0],
            lshape)
        spec = igg.spec_for(len(lshape))
        oracle = jax.jit(jax.shard_map(
            lambda A: self._sequential_oracle(A, grid),
            mesh=grid.mesh, in_specs=spec, out_specs=spec))
        exp = np.array(oracle(field))
        out = np.array(igg.update_halo(field))
        np.testing.assert_array_equal(out, exp)

    @pytest.mark.parametrize("periods", [
        dict(), dict(periodx=1, periody=1, periodz=1),
        dict(periody=1), dict(periodx=1, periodz=1)])
    def test_random_data(self, periods):
        igg.init_global_grid(6, 6, 6, **periods, quiet=True)  # dims (2,2,2)
        self._check((6, 6, 6))

    def test_random_data_staggered_open(self):
        igg.init_global_grid(6, 6, 6, periodz=1, quiet=True)
        self._check((7, 6, 6))

    def test_random_data_single_device_dims(self):
        igg.init_global_grid(6, 6, 6, dimy=1, dimz=1, periody=1, quiet=True)
        self._check((6, 6, 6))


class TestAssemblyForce:
    """assembly='pallas' is a real force (ADVICE r3): it raises where the
    writers cannot serve the call instead of silently falling back."""

    def test_rejected_on_cpu_mesh(self):
        igg.init_global_grid(6, 6, 6, **PERIODIC, quiet=True)
        A = igg.zeros((6, 6, 6))
        with pytest.raises(igg.GridError, match="requires TPU"):
            igg.update_halo(A, assembly="pallas")

    def test_rejected_for_unsupported_field_via_seam(self):
        igg.init_global_grid(6, 6, 6, **PERIODIC, quiet=True)
        halo._FORCE_WRITER_INTERPRET = True
        try:
            A = igg.zeros((6, 6, 6, 2))   # rank-4: writers are rank-3 only
            with pytest.raises(igg.GridError, match="do not support"):
                igg.update_halo(A, assembly="pallas")
        finally:
            halo._FORCE_WRITER_INTERPRET = False

    def test_accepted_for_supported_field_via_seam(self):
        igg.init_global_grid(8, 16, 256, **PERIODIC, quiet=True)
        halo._FORCE_WRITER_INTERPRET = True
        try:
            out, exp = roundtrip((8, 16, 256), dtype=np.float32)
            np.testing.assert_array_equal(out, exp.astype(np.float32))
        finally:
            halo._FORCE_WRITER_INTERPRET = False


class TestMeasuredAssemblyDispatch:
    def test_cpu_shortcut_builds_only_xla(self):
        """On CPU meshes the model dispatch must not measure (the writers
        never engage; 'xla' and default compile identical programs)."""
        from igg.models._dispatch import measured_assembly_path

        igg.init_global_grid(6, 6, 6, quiet=True)
        built = []

        def build(assembly):
            built.append(assembly)
            return lambda *args: args[0]

        import jax.numpy as jnp
        d = measured_assembly_path(build, tag="test", wrap=lambda f: f)
        d(jnp.zeros((6, 6, 6)))
        assert built == ["xla"]

    def test_election_survives_noisy_timer(self):
        """VERDICT r4 weak item 6: a single noisy measurement must not pin
        the wrong variant.  The injected timer gives 'xla' one spuriously
        fast first sample (single-shot election would pick it); the
        median-of-k close-margin re-measure elects the true winner."""
        from igg.models._dispatch import _elect

        # true costs: xla ~0.110 s, writer ~0.100 s; first xla sample is a
        # noisy 0.090 (20% low).
        scripted = {"xla": [0.090, 0.112, 0.111],
                    "writer": [0.100, 0.099, 0.098]}
        calls = {"xla": 0, "writer": 0}

        def measure(name):
            v = scripted[name][calls[name]]
            calls[name] += 1
            return v

        assert _elect(measure) == "writer"
        assert calls["xla"] >= 2  # it actually re-measured

    def test_election_fast_path_when_separated(self):
        """Well-separated variants are elected after ONE measurement each
        (the measurement cost stays two compiles + two timings)."""
        from igg.models._dispatch import _elect

        calls = {"xla": 0, "writer": 0}

        def measure(name):
            calls[name] += 1
            return {"xla": 0.200, "writer": 0.100}[name]

        assert _elect(measure) == "writer"
        assert calls == {"xla": 1, "writer": 1}


class TestEndToEnd4D:
    """Rank-4 component-stacked fields `(nx, ny, nz, C)` (VERDICT r3 item
    6): trailing dims are unsharded, planes carry the component axis —
    the analog of the reference's rank-generic `GGArray{T,N}`
    (`/root/reference/src/shared.jl:32`)."""

    def test_periodic_multidevice(self):
        from helpers import assert_halo_agreement

        igg.init_global_grid(6, 6, 6, **PERIODIC, quiet=True)
        out, exp = roundtrip((6, 6, 6, 3))
        np.testing.assert_array_equal(out, exp)
        assert_halo_agreement(out, (6, 6, 6, 3))

    def test_open_boundaries(self):
        from helpers import assert_halo_agreement

        igg.init_global_grid(6, 6, 6, quiet=True)
        out, exp = roundtrip((6, 6, 6, 3))
        np.testing.assert_array_equal(out, exp)
        # Open dims have no wrap pair; interior-pair overlap still agrees.
        assert_halo_agreement(out, (6, 6, 6, 3))

    def test_staggered_rank4(self):
        from helpers import assert_halo_agreement

        igg.init_global_grid(6, 6, 6, **PERIODIC, quiet=True)
        out, exp = roundtrip((7, 6, 6, 2))   # x-staggered component field
        np.testing.assert_array_equal(out, exp)
        assert_halo_agreement(out, (7, 6, 6, 2))

    def test_grouped_mixed_rank(self):
        """One grouped update mixing a rank-3 and a rank-4 field (the
        engine groups same-plane-shape fields for the wire; mixed ranks
        must exchange independently but correctly in one program)."""
        import jax
        from helpers import (encoded_field, expected_after_update,
                             zero_halo_blocks)

        igg.init_global_grid(6, 6, 6, periody=1, quiet=True)
        shapes = [(6, 6, 6), (6, 6, 6, 3)]
        fields, backs, zeroed = [], [], []
        for ls in shapes:
            f = encoded_field(ls)
            b = np.array(f)
            z = zero_halo_blocks(b, ls)
            fields.append(jax.device_put(z, igg.sharding_for(len(ls))))
            backs.append(b)
            zeroed.append(z)
        outs = igg.update_halo(*fields)
        for out, b, z, ls in zip(outs, backs, zeroed, shapes):
            np.testing.assert_array_equal(
                np.array(out), expected_after_update(b, z, ls))

    def test_rank4_inside_sharded(self):
        """update_halo_local on a rank-4 field inside `igg.sharded` — the
        SPMD path a user's component-stacked solver runs."""
        import jax
        from helpers import (encoded_field, expected_after_update,
                             zero_halo_blocks)

        igg.init_global_grid(6, 6, 6, **PERIODIC, quiet=True)

        @igg.sharded
        def step(A):
            return igg.update_halo_local(A)

        ls = (6, 6, 6, 2)
        f = encoded_field(ls)
        b = np.array(f)
        z = zero_halo_blocks(b, ls)
        out = np.array(step(jax.device_put(z, igg.sharding_for(4))))
        np.testing.assert_array_equal(out, expected_after_update(b, z, ls))


class TestEndToEnd2D1D:
    def test_2d(self):
        igg.init_global_grid(6, 6, 1, periodx=1, quiet=True)  # dims (4,2,1)
        out, exp = roundtrip((6, 6))
        np.testing.assert_array_equal(out, exp)

    def test_1d(self):
        igg.init_global_grid(8, 1, 1, periodx=1, quiet=True)  # dims (8,1,1)
        out, exp = roundtrip((8,))
        np.testing.assert_array_equal(out, exp)

    def test_1d_open(self):
        igg.init_global_grid(8, 1, 1, quiet=True)
        out, exp = roundtrip((8,))
        np.testing.assert_array_equal(out, exp)


class TestDisp:
    """`disp` is honored: exchange partners sit `disp` ranks away, the
    `MPI.Cart_shift` semantics the reference builds its neighbor table with
    (`/root/reference/src/init_global_grid.jl:78-81`)."""

    @staticmethod
    def _rank_blocks(nx):
        return igg.from_local_blocks(
            lambda coords, ls: np.full(ls, float(coords[0])), (nx, 2, 2))

    def test_disp2_periodic(self):
        igg.init_global_grid(8, 2, 2, dimx=8, dimy=1, dimz=1, periodx=1,
                             disp=2, quiet=True)
        out = np.array(igg.update_halo(self._rank_blocks(8)))
        for c in range(8):
            blk = out[c * 8:(c + 1) * 8]
            assert blk[0, 0, 0] == (c - 2) % 8, (c, blk[0, 0, 0])
            assert blk[-1, 0, 0] == (c + 2) % 8, (c, blk[-1, 0, 0])
        g = igg.get_global_grid()
        assert g.neighbors_of((3, 0, 0), 0) == (g.cart_rank((1, 0, 0)),
                                                g.cart_rank((5, 0, 0)))

    def test_disp2_open_edges_keep_stale(self):
        igg.init_global_grid(8, 2, 2, dimx=8, dimy=1, dimz=1, disp=2,
                             quiet=True)
        out = np.array(igg.update_halo(self._rank_blocks(8)))
        for c in range(8):
            blk = out[c * 8:(c + 1) * 8]
            # ranks 0/1 have no left partner, 6/7 no right partner:
            # the no-write (stale) semantics of open boundaries.
            exp_first = float(c) if c < 2 else (c - 2)
            exp_last = float(c) if c >= 6 else (c + 2)
            assert blk[0, 0, 0] == exp_first, (c, blk[0, 0, 0])
            assert blk[-1, 0, 0] == exp_last, (c, blk[-1, 0, 0])

    def test_disp_wrap_multiple_is_self_copy(self):
        # disp == 2 on a periodic 2-device axis: every rank is its own
        # partner — halos come from the rank's own inner planes.
        igg.init_global_grid(6, 6, 2, dimx=2, dimy=2, dimz=2, periodx=1,
                             disp=2, quiet=True)
        A = igg.from_local_blocks(
            lambda coords, ls: np.full(ls, float(coords[0])), (6, 6, 2))
        out = np.array(igg.update_halo(A))
        for c in range(2):
            blk = out[c * 6:(c + 1) * 6]
            assert (blk[0] == c).all() and (blk[-1] == c).all()

    def test_disp_nonpositive_rejected(self):
        with pytest.raises(igg.GridError, match="disp"):
            igg.init_global_grid(8, 8, 8, disp=0, quiet=True)
        with pytest.raises(igg.GridError, match="disp"):
            igg.init_global_grid(8, 8, 8, disp=-1, quiet=True)


class TestMultiField:
    def test_two_fields_at_once(self):
        igg.init_global_grid(6, 6, 6, **PERIODIC, quiet=True)
        import jax
        from helpers import (encoded_field, expected_after_update,
                             zero_halo_blocks)
        fields, backs, zeros_ = [], [], []
        for lshape in [(6, 6, 6), (7, 6, 6)]:
            f = encoded_field(lshape)
            b = np.array(f)
            z = zero_halo_blocks(b, lshape)
            fields.append(jax.device_put(z, igg.sharding_for(len(lshape))))
            backs.append(b)
            zeros_.append(z)
        outA, outB = igg.update_halo(*fields)
        np.testing.assert_array_equal(
            np.array(outA), expected_after_update(backs[0], zeros_[0], (6, 6, 6)))
        np.testing.assert_array_equal(
            np.array(outB), expected_after_update(backs[1], zeros_[1], (7, 6, 6)))

    def test_compile_cache_reuse(self):
        igg.init_global_grid(6, 6, 6, **PERIODIC, quiet=True)
        A = igg.zeros((6, 6, 6))
        A = igg.update_halo(A)
        n = len(halo._compiled)
        A = igg.update_halo(A)
        assert len(halo._compiled) == n  # same signature -> no new program
        B = igg.zeros((6, 6, 6), dtype=np.float64)
        igg.update_halo(B)
        assert len(halo._compiled) == n + 1  # new dtype -> new program


class TestDtypes:
    @pytest.mark.parametrize("dtype", [np.float32, np.float64, np.float16,
                                       np.complex64, np.complex128])
    def test_dtype_roundtrip(self, dtype):
        # complex64/complex128 ride the XLA fallback plans (no writer
        # support), matching the reference's any-Number element contract
        # (`/root/reference/src/shared.jl:31`, ComplexF16 end-to-end in
        # `/root/reference/test/test_update_halo.jl` §2/§4).
        igg.init_global_grid(6, 6, 6, **PERIODIC, quiet=True)
        out, exp = roundtrip((6, 6, 6), dtype=dtype)
        np.testing.assert_array_equal(out, exp.astype(dtype))

    @pytest.mark.parametrize("dtype", [np.complex64, np.complex128])
    def test_complex_open_boundaries(self, dtype):
        igg.init_global_grid(6, 6, 6, quiet=True)  # (2,2,2), all open
        out, exp = roundtrip((6, 6, 6), dtype=dtype)
        np.testing.assert_array_equal(out, exp.astype(dtype))

    @pytest.mark.parametrize("shape,dims", [
        ((5, 6, 7), [0]), ((5, 6, 7), [1]), ((5, 6, 7), [2]),
        ((5, 6, 7), [0, 1]), ((5, 6, 7), [0, 1, 2]),
        ((6, 7), [0, 1]), ((4, 5, 6, 3), [0, 1, 2]),
    ])
    @pytest.mark.parametrize("dtype", [np.float64, np.complex128])
    def test_dus64_plan_matches_select(self, shape, dims, dtype):
        """The all-DUS 'dus64' assembly form writes exactly what the
        reference select plan writes, for every rank and participating-dim
        subset (lane-active sets included: production `_assembly_plan`
        routes those to 'select' on TPU, but the forced-plan equivalence
        pins that the two forms are interchangeable wherever either is
        chosen — see `igg.halo._assembly_plan` for the measured rules)."""
        from igg.halo import _assembly_plan, assemble_planes

        rng = np.random.default_rng(7)
        def mk(s):
            a = rng.standard_normal(s)
            return (a + 1j * rng.standard_normal(s)
                    if np.dtype(dtype).kind == "c" else a).astype(dtype)
        A = mk(shape)
        recv = {}
        for d in dims:
            ps = list(shape)
            ps[d] = 1
            recv[d] = (mk(tuple(ps)), mk(tuple(ps)))
        dims_active = [(d, 2) for d in dims]
        got = np.array(assemble_planes(A, recv, dims_active, plan="dus64"))
        ref = np.array(assemble_planes(A, recv, dims_active, plan="select"))
        np.testing.assert_array_equal(got, ref)
        # Auto-selection on TPU: 'select' for lane-active pair sets (one
        # fused pass — a lane DUS costs a relayout pass), 'dus64' for the
        # rest (`_assembly_plan` docstring).
        lane_active = (len(shape) - 1) in dims
        plan = _assembly_plan(shape, dtype, dims, on_tpu=True)
        assert plan == ("select" if lane_active else "dus64")
        assert _assembly_plan(shape, dtype, dims) in ("dus", "select")
        assert _assembly_plan(shape, np.float32, dims, on_tpu=True) != "dus64"

    def test_bfloat16(self):
        import jax.numpy as jnp
        igg.init_global_grid(6, 6, 6, **PERIODIC, quiet=True)
        # small integer-valued encoding is exact in bf16 up to 256
        import jax
        from helpers import encoded_field, zero_halo_blocks, expected_after_update
        f64 = encoded_field((6, 6, 6))
        b = np.array(f64) % 64  # keep values bf16-exact
        z = zero_halo_blocks(b, (6, 6, 6))
        A = jax.device_put(z.astype(jnp.bfloat16), igg.sharding_for(3))
        out = np.array(igg.update_halo(A).astype(np.float64))
        np.testing.assert_array_equal(out, expected_after_update(b, z, (6, 6, 6)))


class TestLocalForm:
    def test_update_halo_local_inside_sharded(self):
        igg.init_global_grid(6, 6, 6, **PERIODIC, quiet=True)
        import jax
        from helpers import encoded_field, zero_halo_blocks, expected_after_update

        @igg.sharded
        def step(A):
            return igg.update_halo_local(A)

        f = encoded_field((6, 6, 6))
        b = np.array(f)
        z = zero_halo_blocks(b, (6, 6, 6))
        out = np.array(step(jax.device_put(z, igg.sharding_for(3))))
        np.testing.assert_array_equal(out, expected_after_update(b, z, (6, 6, 6)))


# ---------------------------------------------------------------------------
# One-pass in-place Pallas writer (igg/ops/halo_write.py), interpret mode.
# On TPU this kernel performs the assembly whenever the lane dim participates;
# here its semantics are pinned against a numpy oracle for every source-mode
# combination the engine generates.
# ---------------------------------------------------------------------------

class TestHaloWriter:
    @staticmethod
    def _oracle(A, specs):
        ref = np.array(A, dtype=np.float64).copy()
        nd = ref.ndim
        for s in specs:
            d = s[0]
            sl0, sl1 = [slice(None)] * nd, [slice(None)] * nd
            sl0[d], sl1[d] = 0, ref.shape[d] - 1
            if s[1] == "ext":
                ref[tuple(sl0)] = np.asarray(s[2], dtype=np.float64)
                ref[tuple(sl1)] = np.asarray(s[3], dtype=np.float64)
            else:
                ol = s[2]
                src0, src1 = [slice(None)] * nd, [slice(None)] * nd
                src0[d], src1[d] = ref.shape[d] - ol, ol - 1
                ref[tuple(sl0)] = ref[tuple(src0)]
                ref[tuple(sl1)] = ref[tuple(src1)]
        return ref

    @pytest.mark.parametrize("modes", [
        ("ext", "ext", "ext"),
        ("ext", "wrap", "wrap"),
        ("ext", "ext", "wrap"),
        ("ext", "wrap", "ext"),
        (None, "wrap", "wrap"),
        (None, None, "ext"),
        (None, None, "wrap"),
        ("ext", None, "wrap"),
    ])
    @pytest.mark.parametrize("dtype", [np.float32, "bfloat16", np.float64])
    def test_against_oracle(self, modes, dtype):
        import jax.numpy as jnp
        from igg.ops.halo_write import halo_write

        if dtype == "bfloat16":
            dtype = jnp.bfloat16
        rng = np.random.default_rng(42)
        shape = (8, 10, 12)
        A = jnp.asarray(rng.integers(0, 63, shape), dtype=dtype)
        specs = []
        plane_shapes = {0: (10, 12), 1: (8, 12), 2: (8, 10)}
        for d, mode in enumerate(modes):
            if mode is None:
                continue
            if mode == "ext":
                specs.append((d, "ext",
                              jnp.asarray(rng.integers(0, 63,
                                                       plane_shapes[d]),
                                          dtype=dtype),
                              jnp.asarray(rng.integers(0, 63,
                                                       plane_shapes[d]),
                                          dtype=dtype)))
            else:
                specs.append((d, "wrap", 2 + d % 2))
        out = halo_write(A, specs, interpret=True)
        exp = self._oracle(A, specs)
        np.testing.assert_array_equal(
            np.array(out, dtype=np.float64), exp)

    def test_dim0_wrap_rejected(self):
        import jax.numpy as jnp
        from igg.ops.halo_write import halo_write

        A = jnp.zeros((8, 8, 8))
        with pytest.raises(ValueError, match="dim-0 wrap"):
            halo_write(A, [(0, "wrap", 2)], interpret=True)


class TestSlabWriters:
    """Per-dim in-place slab writers (non-lane halo sets), interpret mode."""

    @pytest.mark.parametrize("modes", [
        ("ext", None), ("ext", "ext"), ("ext", "wrap"),
        (None, "ext"), (None, "wrap"),
    ])
    @pytest.mark.parametrize("dtype", [np.float32, "bfloat16", np.float64])
    def test_against_oracle(self, modes, dtype):
        import jax.numpy as jnp
        from igg.ops.halo_write import _sublane_tile, halo_write_slabs

        if dtype == "bfloat16":
            dtype = jnp.bfloat16
        ts = _sublane_tile(np.dtype(dtype).itemsize)
        n1 = 4 * ts  # tile-aligned with distinct first/last tiles
        rng = np.random.default_rng(3)
        shape = (8, n1, 12)
        A = jnp.asarray(rng.integers(0, 63, shape), dtype=dtype)
        specs = []
        plane_shapes = {0: (n1, 12), 1: (8, 12)}
        for d, mode in enumerate(modes):
            if mode is None:
                continue
            if mode == "ext":
                specs.append((d, "ext",
                              jnp.asarray(rng.integers(0, 63,
                                                       plane_shapes[d]),
                                          dtype=dtype),
                              jnp.asarray(rng.integers(0, 63,
                                                       plane_shapes[d]),
                                          dtype=dtype)))
            else:
                specs.append((d, "wrap", 3))
        out = halo_write_slabs(A, specs, interpret=True)
        exp = TestHaloWriter._oracle(A, specs)
        np.testing.assert_array_equal(np.array(out, dtype=np.float64), exp)


class TestWriterEngineIntegration:
    """Drive the ENGINE's writer path (spec building, wrap/ext
    classification, squeeze axes, recv wiring in `_update_halo_impl`) on the
    CPU mesh via the `_FORCE_WRITER_INTERPRET` seam — without it, that
    branch only runs on real TPU hardware."""

    @pytest.fixture(autouse=True)
    def force_writer(self):
        halo._FORCE_WRITER_INTERPRET = True
        yield
        halo._FORCE_WRITER_INTERPRET = False

    # Lane-active sets -> one-pass writer.  n2 must satisfy the aligned
    # plan (multiple of 128, >= 256); n1 the sublane tile.
    @pytest.mark.parametrize("dims,periods", [
        ((2, 2, 2), (1, 1, 1)),   # all dims exchanged (ext specs)
        ((2, 1, 1), (1, 1, 1)),   # y/z wrap (in-VMEM), x exchanged
        ((1, 2, 4), (1, 1, 1)),   # dim-0 wrap (lazy ext), y/z exchanged
        ((2, 2, 2), (0, 1, 1)),   # open x boundary through the writer
    ])
    def test_lane_active_roundtrip(self, dims, periods):
        igg.init_global_grid(8, 16, 256, dimx=dims[0], dimy=dims[1],
                             dimz=dims[2], periodx=periods[0],
                             periody=periods[1], periodz=periods[2],
                             quiet=True)
        from igg.halo import _writer_dims, active_dims, moving_dims
        g = igg.get_global_grid()
        dd = moving_dims(active_dims((8, 16, 256), g), g)
        assert _writer_dims(igg.zeros((8, 16, 256), dtype=np.float32),
                            dd, g)[1], "writer gate must be on"
        out, exp = roundtrip((8, 16, 256), dtype=np.float32)
        np.testing.assert_array_equal(out, exp.astype(np.float32))

    def test_lane_active_roundtrip_float64(self):
        """VERDICT round-3 item 4: the Julia-default Float64 runs the
        deterministic writer path (u32 lane-paired view), not the XLA
        compile-lottery plans."""
        igg.init_global_grid(8, 16, 256, dimx=2, dimy=2, dimz=2,
                             **PERIODIC, quiet=True)
        from igg.halo import _writer_dims, active_dims, moving_dims
        g = igg.get_global_grid()
        dd = moving_dims(active_dims((8, 16, 256), g), g)
        assert _writer_dims(igg.zeros((8, 16, 256), dtype=np.float64),
                            dd, g)[1], "writer gate must be on for f64"
        out, exp = roundtrip((8, 16, 256), dtype=np.float64)
        np.testing.assert_array_equal(out, exp.astype(np.float64))

    # Non-lane sets -> slab writers.
    @pytest.mark.parametrize("dims,periods", [
        ((2, 4, 1), (1, 1, 0)),   # x/y exchanged, z inactive
        ((2, 1, 4), (1, 1, 0)),   # y wrap slab (source-slab refs), x, z off
        ((4, 2, 1), (0, 1, 0)),   # open x through the slab writer
    ])
    def test_slab_roundtrip(self, dims, periods):
        igg.init_global_grid(8, 16, 12, dimx=dims[0], dimy=dims[1],
                             dimz=dims[2], periodx=periods[0],
                             periody=periods[1], periodz=periods[2],
                             quiet=True)
        out, exp = roundtrip((8, 16, 12), dtype=np.float32)
        np.testing.assert_array_equal(out, exp.astype(np.float32))


class TestLaneColumnWriter:
    """Dirty-column lane writer (_write_dim2): exchanged z halos spanning
    >2 tile columns RMW only the two dirty columns."""

    @pytest.mark.parametrize("dtype", [np.float32, "bfloat16", np.float64])
    def test_unit_oracle(self, dtype):
        import jax.numpy as jnp
        from igg.ops.halo_write import _write_dim2

        if dtype == "bfloat16":
            dtype = jnp.bfloat16
        rng = np.random.default_rng(6)
        A = jnp.asarray(rng.integers(0, 63, (8, 10, 384)), dtype=dtype)
        pf = jnp.asarray(rng.integers(0, 63, (8, 10)), dtype=dtype)
        pq = jnp.asarray(rng.integers(0, 63, (8, 10)), dtype=dtype)
        out = _write_dim2(A, (2, "ext", pf, pq), interpret=True)
        exp = np.array(A, dtype=np.float64)
        exp[:, :, 0] = np.asarray(pf, dtype=np.float64)
        exp[:, :, -1] = np.asarray(pq, dtype=np.float64)
        np.testing.assert_array_equal(np.array(out, np.float64), exp)

    @pytest.mark.parametrize("dims,periods", [
        ((1, 2, 4), (1, 1, 1)),   # z exchanged over 4 devices, x wrap
        ((2, 2, 2), (0, 1, 1)),   # open x + exchanged z
    ])
    def test_engine_roundtrip(self, dims, periods):
        """Engine spec-building through the dirty-column chain (z spans 3
        tile columns -> lane_columns_writable), via the interpret seam."""
        from igg.halo import _writer_dims, active_dims, moving_dims
        from igg.ops.halo_write import lane_columns_writable

        halo._FORCE_WRITER_INTERPRET = True
        try:
            igg.init_global_grid(8, 16, 384, dimx=dims[0], dimy=dims[1],
                                 dimz=dims[2], periodx=periods[0],
                                 periody=periods[1], periodz=periods[2],
                                 quiet=True)
            g = igg.get_global_grid()
            dd = moving_dims(active_dims((8, 16, 384), g), g)
            w, use_writer = _writer_dims(
                igg.zeros((8, 16, 384), dtype=np.float32), dd, g)
            assert use_writer
            assert lane_columns_writable((8, 16, 384), np.float32,
                                         [d for d, _ in dd], w)
            out, exp = roundtrip((8, 16, 384), dtype=np.float32)
            np.testing.assert_array_equal(out, exp.astype(np.float32))
        finally:
            halo._FORCE_WRITER_INTERPRET = False
