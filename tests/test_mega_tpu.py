"""TPU-only: the K-step mega-kernel must match the per-step fused kernel.

The mega-kernel uses manual TPU DMA/semaphores, which have no interpret
mode, so this test can only run against real TPU hardware.  The suite's
conftest pins the CPU backend; run this file with the escape hatch:

    IGG_TPU_TESTS=1 python -m pytest tests/test_mega_tpu.py -q

(`bench.py` also runs the mega path on every TPU benchmark invocation, so
the driver exercises it each round.)
"""

import numpy as np
import pytest

import igg


def _tpu_available() -> bool:
    import jax

    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:
        return False


@pytest.mark.skipif(not _tpu_available(), reason="needs a real TPU chip")
def test_mega_matches_per_step_kernel():
    import jax.numpy as jnp

    from igg.models import diffusion3d as d3
    from igg.ops.diffusion_mega import fused_diffusion_megasteps, \
        mega_supported

    igg.init_global_grid(64, 64, 128, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    params = d3.Params()
    T, Cp = d3.init_fields(params, dtype=np.float32)
    dx, dy, dz = params.spacing()
    scal = dict(rdx2=1.0 / (dx * dx), rdy2=1.0 / (dy * dy),
                rdz2=1.0 / (dz * dz))
    A = float(params.timestep() * params.lam) / Cp
    assert mega_supported(T.shape, 8, 6, interpret=False, dtype=T.dtype)

    out = fused_diffusion_megasteps(T, A, n_inner=6, bx=8, **scal)

    from igg.ops import fused_diffusion_step
    import jax
    dt = params.timestep()
    ref = T
    step = jax.jit(lambda T: fused_diffusion_step(
        T, Cp, dx=dx, dy=dy, dz=dz, dt=dt, lam=params.lam, bx=8))
    for _ in range(6):
        ref = step(ref)
    scale = float(jnp.max(jnp.abs(ref)))
    assert float(jnp.max(jnp.abs(out - ref))) <= 4e-7 * scale


@pytest.mark.skipif(not _tpu_available(), reason="needs a real TPU chip")
def test_trapezoid_matches_per_step_kernel():
    """The K-step trapezoidal chunk kernel (x-exchanged ring; here the
    1-device self-ring) must match K applications of the per-step fused
    kernel on the same block."""
    import jax
    import jax.numpy as jnp

    from igg.models import diffusion3d as d3
    from igg.ops.diffusion_trapezoid import (
        fused_diffusion_trapezoid_steps, trapezoid_supported)

    igg.init_global_grid(64, 64, 128, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    grid = igg.get_global_grid()
    params = d3.Params()
    T, Cp = d3.init_fields(params, dtype=np.float32)
    # The trapezoid's validity argument requires exchange-fresh halos at
    # chunk entry (any state after update_halo or a model step qualifies;
    # raw init_fields coordinates do not).
    T = igg.update_halo(T)
    dx, dy, dz = params.spacing()
    scal = dict(rdx2=1.0 / (dx * dx), rdy2=1.0 / (dy * dy),
                rdz2=1.0 / (dz * dz))
    A = float(params.timestep() * params.lam) / Cp
    bx = 8
    assert trapezoid_supported(grid, T.shape, bx, 2 * bx, T.dtype)

    out, done = jax.jit(
        lambda T, A: fused_diffusion_trapezoid_steps(
            T, A, n_inner=2 * bx, bx=bx, grid=grid, **scal))(T, A)
    assert done == 2 * bx

    from igg.ops import fused_diffusion_step
    dt = params.timestep()
    ref = T
    step = jax.jit(lambda T: fused_diffusion_step(
        T, Cp, dx=dx, dy=dy, dz=dz, dt=dt, lam=params.lam, bx=bx))
    for _ in range(2 * bx):
        ref = step(ref)
    scale = float(jnp.max(jnp.abs(ref)))
    assert float(jnp.max(jnp.abs(out - ref))) <= 4e-7 * scale


@pytest.mark.skipif(not _tpu_available(), reason="needs a real TPU chip")
def test_trapezoid_2d_kernel_matches_window():
    """The y-extended (N,M,1) chunk kernel against the pure-XLA window
    dynamics on the same doubly-extended buffer (the window-vs-per-step
    equivalence is pinned on the CPU torus by tests/test_trapezoid.py)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from igg.models import diffusion3d as d3
    from igg.ops.diffusion_pallas import _u_rows
    from igg.ops.diffusion_trapezoid import _chunk_call, _extend_dim

    igg.init_global_grid(64, 64, 128, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    grid = igg.get_global_grid()
    params = d3.Params()
    T, Cp = d3.init_fields(params, dtype=np.float32)
    T = igg.update_halo(T)
    dx, dy, dz = params.spacing()
    scal = dict(rdx2=1.0 / (dx * dx), rdy2=1.0 / (dy * dy),
                rdz2=1.0 / (dz * dz))
    A = float(params.timestep() * params.lam) / Cp
    K = bx = 8

    def extend2(F):
        F = _extend_dim(F, K, 2, grid, 0)
        return _extend_dim(F, K, 2, grid, 1)

    Text = jax.jit(extend2)(T)
    A_ext = jax.jit(extend2)(A)

    out = jax.jit(lambda Text, A_ext: _chunk_call(
        Text, A_ext, T.shape, K=K, bx=bx,
        modes=("ext", "ext", "wrap"), grid=grid,
        **scal))(Text, A_ext)

    def window(Text, A_ext):
        def step(_, U):
            S2 = U.shape[2]
            U = U.at[1:-1, 1:-1, 1:-1].set(
                _u_rows(U[:-2], U[1:-1], U[2:], A_ext[1:-1], **scal))
            U = U.at[:, :, 0].set(U[:, :, S2 - 2])
            U = U.at[:, :, S2 - 1].set(U[:, :, 1])
            return U
        U = lax.fori_loop(0, K, step, Text)
        return U[K:K + T.shape[0], K:K + T.shape[1]]

    ref = jax.jit(window)(Text, A_ext)
    scale = float(jnp.max(jnp.abs(ref)))
    assert float(jnp.max(jnp.abs(out - ref))) <= 4e-7 * scale


@pytest.mark.skipif(not _tpu_available(), reason="needs a real TPU chip")
def test_trapezoid_3d_kernel_matches_window():
    """The triply-extended (N,M,K) 3-D-torus chunk kernel against the
    pure-XLA window dynamics on the same extended buffer (VERDICT round-3
    item 2; the window-vs-per-step equivalence is pinned on the CPU (2,2,2)
    torus by tests/test_trapezoid.py)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from igg.models import diffusion3d as d3
    from igg.ops.diffusion_pallas import _u_rows
    from igg.ops.diffusion_trapezoid import _chunk_call, _extend_dim

    igg.init_global_grid(64, 64, 128, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    grid = igg.get_global_grid()
    params = d3.Params()
    T, Cp = d3.init_fields(params, dtype=np.float32)
    T = igg.update_halo(T)
    dx, dy, dz = params.spacing()
    scal = dict(rdx2=1.0 / (dx * dx), rdy2=1.0 / (dy * dy),
                rdz2=1.0 / (dz * dz))
    A = float(params.timestep() * params.lam) / Cp
    K = bx = 8

    def extend3(F):
        F = _extend_dim(F, K, 2, grid, 0)
        F = _extend_dim(F, K, 2, grid, 1)
        return _extend_dim(F, K, 2, grid, 2)

    Text = jax.jit(extend3)(T)
    A_ext = jax.jit(extend3)(A)

    out = jax.jit(lambda Text, A_ext: _chunk_call(
        Text, A_ext, T.shape, K=K, bx=bx,
        modes=("ext", "ext", "ext"), grid=grid,
        **scal))(Text, A_ext)

    def window(Text, A_ext):
        def step(_, U):
            return U.at[1:-1, 1:-1, 1:-1].set(
                _u_rows(U[:-2], U[1:-1], U[2:], A_ext[1:-1], **scal))
        U = lax.fori_loop(0, K, step, Text)
        return U[K:K + T.shape[0], K:K + T.shape[1], K:K + T.shape[2]]

    ref = jax.jit(window)(Text, A_ext)
    scale = float(jnp.max(jnp.abs(ref)))
    assert float(jnp.max(jnp.abs(out - ref))) <= 4e-7 * scale


@pytest.mark.skipif(not _tpu_available(), reason="needs a real TPU chip")
@pytest.mark.parametrize("periods", [(0, 0, 0), (0, 1, 1), (1, 0, 1),
                                     (1, 1, 0)])
def test_trapezoid_open_modes_match_per_step_kernel(periods):
    """Round 6: the open-boundary (frozen-edge) chunk kernel modes vs 2K
    applications of the per-step fused kernel — the reference-default
    boundary condition on the compiled K-step tier.  On one chip the open
    dims run "frozen" (periodic dims "ext"/"wrap"), exercising the
    edge-freeze planes, the SMEM flags, and the off=0 frozen-x program
    layout; the multi-device "oext" flag gating is pinned on the 8-device
    interpret meshes (tests/test_trapezoid.py::test_open_*) and by
    test_trapezoid_oext_kernel_matches_window below."""
    import jax
    import jax.numpy as jnp

    from igg.models import diffusion3d as d3
    from igg.ops import fused_diffusion_step
    from igg.ops.diffusion_trapezoid import (
        fused_diffusion_trapezoid_steps, trapezoid_supported)

    igg.init_global_grid(64, 64, 128, dimx=1, dimy=1, dimz=1,
                         periodx=periods[0], periody=periods[1],
                         periodz=periods[2], quiet=True)
    grid = igg.get_global_grid()
    params = d3.Params()
    T, Cp = d3.init_fields(params, dtype=np.float32)
    # Exchange-fresh entry state (frozen dims need nothing; wrap/ext dims
    # need their self-wrap halos fresh, like every trapezoid entry).
    T = igg.update_halo(T)
    dx, dy, dz = params.spacing()
    scal = dict(rdx2=1.0 / (dx * dx), rdy2=1.0 / (dy * dy),
                rdz2=1.0 / (dz * dz))
    A = float(params.timestep() * params.lam) / Cp
    bx = 8
    assert trapezoid_supported(grid, T.shape, bx, 2 * bx, T.dtype,
                               allow_open=True)

    out, done = jax.jit(
        lambda T, A: fused_diffusion_trapezoid_steps(
            T, A, n_inner=2 * bx, bx=bx, grid=grid, **scal))(T, A)
    assert done == 2 * bx

    dt = params.timestep()
    ref = T
    step = jax.jit(lambda T: fused_diffusion_step(
        T, Cp, dx=dx, dy=dy, dz=dz, dt=dt, lam=params.lam, bx=bx))
    for _ in range(2 * bx):
        ref = step(ref)
    scale = float(jnp.max(jnp.abs(ref)))
    assert float(jnp.max(jnp.abs(out - ref))) <= 4e-7 * scale
    # Frozen boundary planes must match BITWISE: both paths leave them
    # untouched (no-write), so they carry the entry values exactly.
    outn, refn, Tn = np.asarray(out), np.asarray(ref), np.asarray(T)
    for d, p in enumerate(periods):
        if p:
            continue
        for edge in (slice(0, 1), slice(-1, None)):
            sl = [slice(None)] * 3
            sl[d] = edge
            assert np.array_equal(outn[tuple(sl)], refn[tuple(sl)]), d
            assert np.array_equal(outn[tuple(sl)], Tn[tuple(sl)]), d
    igg.finalize_global_grid()


@pytest.mark.skipif(not _tpu_available(), reason="needs a real TPU chip")
def test_trapezoid_oext_kernel_matches_window():
    """Round 6: the multi-device open program shape ("oext" — extended by
    non-wrapping permutes, global-edge devices re-freeze their boundary
    plane from SMEM-flag-gated VMEM freeze planes) against the pure-XLA
    window realization on the same extended buffer.  On the 1-chip mesh
    the single device is BOTH global edges, so both freeze planes and
    both `axis_index` flags are exercised; the window realization is
    itself pinned per-step-equivalent on 8-device open meshes by
    tests/test_trapezoid.py."""
    import jax.numpy as jnp

    from igg.models import diffusion3d as d3
    from igg.ops.diffusion_trapezoid import _chunk_call, _extend

    igg.init_global_grid(64, 64, 128, dimx=1, dimy=1, dimz=1,
                         periodx=0, periody=1, periodz=1, quiet=True)
    grid = igg.get_global_grid()
    params = d3.Params()
    T, Cp = d3.init_fields(params, dtype=np.float32)
    T = igg.update_halo(T)
    dx, dy, dz = params.spacing()
    scal = dict(rdx2=1.0 / (dx * dx), rdy2=1.0 / (dy * dy),
                rdz2=1.0 / (dz * dz))
    A = float(params.timestep() * params.lam) / Cp
    K = bx = 8
    modes = ("oext", "ext", "wrap")
    shape = T.shape

    @igg.sharded
    def kernel_chunk(T, A):
        Text = _extend(T, K, grid, shape, modes)
        A_ext = _extend(A, K, grid, shape, modes)
        return _chunk_call(Text, A_ext, shape, K=K, bx=bx, modes=modes,
                           grid=grid, **scal)

    @igg.sharded
    def window_chunk(T, A):
        Text = _extend(T, K, grid, shape, modes)
        A_ext = _extend(A, K, grid, shape, modes)
        return _chunk_call(Text, A_ext, shape, K=K, bx=bx, modes=modes,
                           grid=grid, **scal, interpret=True)

    out = np.asarray(kernel_chunk(T, A))
    ref = np.asarray(window_chunk(T, A))
    scale = max(abs(ref).max(), 1e-30)
    assert abs(out - ref).max() <= 4e-7 * scale
    igg.finalize_global_grid()


@pytest.mark.skipif(not _tpu_available(), reason="needs a real TPU chip")
def test_stokes_kernel_compiled_matches_xla():
    """Round 4: the mesh-capable fused Stokes kernel COMPILED on the chip
    (engine-routed x planes, staggered per-field halo modes) vs the XLA
    composition — the interpret-mode equivalence is pinned on CPU by
    tests/test_stokes_pallas.py; this pins the Mosaic lowering."""
    import jax.numpy as jnp

    from igg.models import stokes3d

    igg.init_global_grid(64, 64, 64, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1,
                         overlapx=3, overlapy=3, overlapz=3, quiet=True)
    params = stokes3d.Params()
    fields = stokes3d.init_fields(params, dtype=np.float32)
    it_x = stokes3d.make_iteration(params, n_inner=2, donate=False,
                                   use_pallas=False)
    it_p = stokes3d.make_iteration(params, n_inner=2, donate=False,
                                   use_pallas=True)
    Sx = Sp = fields[:4]
    Rho = fields[4]
    for _ in range(2):
        Sx = it_x(*Sx, Rho)
        Sp = it_p(*Sp, Rho)
    for name, a, b in zip(("P", "Vx", "Vy", "Vz"), Sx, Sp):
        d = float(jnp.max(jnp.abs(a - b)))
        s = float(jnp.max(jnp.abs(a))) + 1e-30
        assert d / s < 1e-5, (name, d, s)
    igg.finalize_global_grid()


@pytest.mark.skipif(not _tpu_available(), reason="needs a real TPU chip")
def test_hm3d_kernel_compiled_matches_xla():
    """Round 4: the mesh-capable fused HM3D kernel COMPILED on the chip
    (engine-routed x planes, single-step emit_slabs=False and the
    slab-carry multi-step) vs the XLA composition."""
    import jax.numpy as jnp

    from igg.models import hm3d

    igg.init_global_grid(64, 64, 128, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    params = hm3d.Params()
    Pe, phi = hm3d.init_fields(params, dtype=np.float32)
    ref = hm3d.make_step(params, n_inner=3, donate=False, use_pallas=False)
    pal = hm3d.make_step(params, n_inner=3, donate=False, use_pallas=True)
    Sr = ref(Pe, phi)
    Sp = pal(Pe, phi)
    for name, a, b in zip(("Pe", "phi"), Sr, Sp):
        d = float(jnp.max(jnp.abs(a - b)))
        s = float(jnp.max(jnp.abs(a))) + 1e-30
        assert d / s < 1e-5, (name, d, s)
    igg.finalize_global_grid()


@pytest.mark.skipif(not _tpu_available(), reason="needs a real TPU chip")
def test_hm3d_mega_matches_per_step_kernel():
    """The two-field K-step HM3D mega-kernel (manual DMA, HBM ping-pong for
    both fields) must match K applications of the per-step fused kernel."""
    import jax
    import jax.numpy as jnp

    from igg.models import hm3d
    from igg.ops.hm3d_mega import fused_hm3d_megasteps, hm3d_mega_supported
    from igg.ops.hm3d_pallas import fused_hm3d_step

    igg.init_global_grid(64, 64, 128, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    params = hm3d.Params()
    Pe, phi = hm3d.init_fields(params, dtype=np.float32)
    Pe, phi = igg.update_halo(Pe, phi)
    dx, dy, dz = params.spacing()
    kw = dict(dx=dx, dy=dy, dz=dz, dt=params.timestep(), phi0=params.phi0,
              npow=params.npow, eta=params.eta)
    assert hm3d_mega_supported(Pe.shape, 8, 6, False, Pe.dtype)

    out = jax.jit(lambda Pe, phi: fused_hm3d_megasteps(
        Pe, phi, n_inner=6, bx=8, **kw))(jnp.array(Pe), jnp.array(phi))

    rp, rf = jnp.array(Pe), jnp.array(phi)
    step = jax.jit(lambda Pe, phi: fused_hm3d_step(Pe, phi, **kw, bx=8))
    for _ in range(6):
        rp, rf = step(rp, rf)
    for name, a, b in (("Pe", out[0], rp), ("phi", out[1], rf)):
        d = float(jnp.max(jnp.abs(a - b)))
        s = float(jnp.max(jnp.abs(b))) + 1e-30
        assert d / s < 1e-6, (name, d, s)
    igg.finalize_global_grid()


@pytest.mark.skipif(not _tpu_available(), reason="needs a real TPU chip")
@pytest.mark.parametrize("periods", [(1, 1, 1), (1, 1, 0)])
def test_f64_halo_oracle_on_chip(periods):
    """Float64 (the reference's default element type) halo exchange on
    real hardware: the barrier-fenced op-mix plans ('select' lane-active,
    'dus64' otherwise — see igg.halo._assembly_plan) must reproduce the
    reference's update semantics exactly, in the device representation.

    The oracle encodes coordinates as small integers, which the x64
    rewriter's float-float pairs represent exactly, so equality is
    bitwise."""
    import jax
    import jax.numpy as jnp

    n = 64
    with jax.enable_x64(True):
        igg.init_global_grid(n, n, n, dimx=1, dimy=1, dimz=1,
                             periodx=periods[0], periody=periods[1],
                             periodz=periods[2], quiet=True)
        i, j, k = np.meshgrid(np.arange(n), np.arange(n), np.arange(n),
                              indexing="ij")
        host = (i * n * n + j * n + k).astype(np.float64)

        out = np.asarray(igg.update_halo(jnp.asarray(host)))

        exp = host.copy()
        for d in range(3):
            if not periods[d]:
                continue  # one open device: planes stay stale (no-write)
            sl_first = [slice(None)] * 3
            sl_last = [slice(None)] * 3
            src_first = [slice(None)] * 3
            src_last = [slice(None)] * 3
            sl_first[d] = 0
            src_first[d] = n - 2
            sl_last[d] = n - 1
            src_last[d] = 1
            exp[tuple(sl_first)] = exp[tuple(src_first)]
            exp[tuple(sl_last)] = exp[tuple(src_last)]
        assert np.array_equal(out, exp), (
            periods, np.argwhere(out != exp)[:5])
        igg.finalize_global_grid()


@pytest.mark.skipif(not _tpu_available(), reason="needs a real TPU chip")
@pytest.mark.parametrize("dtype", ["complex64", "complex128"])
def test_complex_platform_envelope_on_chip(dtype):
    """Pin the documented complex envelope (docs/migration.md): this
    XLA:TPU toolchain's complex support is unreliable — complex128 is
    rejected at tensor creation ('Element type C128 is not supported on
    TPU') and complex64 compiles for some shapes but fails UNIMPLEMENTED
    at halo-class ones (probed here: the eager broadcast to a
    (64,64,128) block) — so igg's complex halo coverage runs on the CPU
    backend (tests/test_update_halo.py) and TPU users carry re/im real
    field pairs.  If a future toolchain accepts the probe, this test
    will fail — the signal to run the full complex oracle on chip and
    update the envelope."""
    # The probe runs in a SUBPROCESS: the rejected compile corrupts the
    # tunneled backend's compile service for subsequent programs in the
    # same process (observed: a later trivial psum failing UNIMPLEMENTED),
    # so it must not share a process with real tests.
    import subprocess
    import sys

    # complex64's acceptance is CONTEXT-dependent (the same (64,64,128)
    # creation passes standalone and fails UNIMPLEMENTED after the grid's
    # init programs have compiled), so the probe reproduces the real
    # usage context: grid init, then a complex halo update.
    prog = (
        "import jax, jax.numpy as jnp\n"
        + ("jax.config.update('jax_enable_x64', True)\n"
           if dtype == "complex128" else "")
        + "import igg\n"
        + "igg.init_global_grid(64, 64, 128, dimx=1, dimy=1, dimz=1,\n"
        + "                     periodx=1, periody=1, periodz=1,\n"
        + "                     quiet=True)\n"
        + "try:\n"
        + f"    y = jnp.ones((64, 64, 128), '{dtype}')\n"
        + "    jax.block_until_ready(igg.update_halo(y * 2))\n"
        + "except Exception as e:\n"
        + "    print('REJECTED:', type(e).__name__)\n"
        + "else:\n"
        + "    print('ACCEPTED')\n")
    out = subprocess.run([sys.executable, "-c", prog], capture_output=True,
                         text=True, timeout=600)
    assert "REJECTED" in out.stdout, (out.stdout, out.stderr[-500:])


@pytest.mark.skipif(not _tpu_available(), reason="needs a real TPU chip")
def test_mega_streamed_a_matches_resident():
    """The slab-streamed coefficient pipeline (round 5 — the mode that
    unlocks local blocks whose A cannot stay VMEM-resident, e.g. the
    512^3 headline) must be bitwise identical to the resident mode: same
    arithmetic, different A sourcing."""
    import jax.numpy as jnp

    from igg.models import diffusion3d as d3
    from igg.ops.diffusion_mega import fused_diffusion_megasteps

    igg.init_global_grid(64, 64, 128, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    params = d3.Params()
    T, Cp = d3.init_fields(params, dtype=np.float32)
    dx, dy, dz = params.spacing()
    scal = dict(rdx2=1.0 / (dx * dx), rdy2=1.0 / (dy * dy),
                rdz2=1.0 / (dz * dz))
    A = float(params.timestep() * params.lam) / Cp

    res = fused_diffusion_megasteps(jnp.array(T), A, n_inner=6, bx=8, **scal)
    stw = fused_diffusion_megasteps(jnp.array(T), A, n_inner=6, bx=8, **scal,
                                    force_streamed=True)
    assert np.array_equal(np.asarray(res), np.asarray(stw))
    igg.finalize_global_grid()


@pytest.mark.skipif(not _tpu_available(), reason="needs a real TPU chip")
@pytest.mark.parametrize("periods", [(0, 0, 0), (0, 1, 1), (1, 1, 0),
                                     (1, 0, 1), (0, 0, 1)])
@pytest.mark.parametrize("streamed", [False, True])
def test_mega_frozen_modes_match_per_step_kernel(periods, streamed):
    """Open-boundary (frozen-edge) mega modes vs K applications of the
    per-step fused kernel, which realizes the no-write halo semantics
    through the engine's stale planes — including the all-open case of
    the reference's published 510^3 headline workload."""
    import jax
    import jax.numpy as jnp

    from igg.models import diffusion3d as d3
    from igg.ops import fused_diffusion_step
    from igg.ops.diffusion_mega import fused_diffusion_megasteps

    igg.init_global_grid(64, 64, 128, dimx=1, dimy=1, dimz=1,
                         periodx=periods[0], periody=periods[1],
                         periodz=periods[2], quiet=True)
    params = d3.Params()
    T, Cp = d3.init_fields(params, dtype=np.float32)
    dx, dy, dz = params.spacing()
    dt = params.timestep()
    scal = dict(rdx2=1.0 / (dx * dx), rdy2=1.0 / (dy * dy),
                rdz2=1.0 / (dz * dz))
    A = float(dt * params.lam) / Cp
    modes = tuple("wrap" if p else "frozen" for p in periods)

    out = fused_diffusion_megasteps(jnp.array(T), A, n_inner=6, bx=8,
                                    **scal, modes=modes,
                                    force_streamed=streamed)

    step = jax.jit(lambda T: fused_diffusion_step(
        T, Cp, dx=dx, dy=dy, dz=dz, dt=dt, lam=params.lam, bx=8))
    ref = jnp.array(T)
    for _ in range(6):
        ref = step(ref)
    scale = float(jnp.max(jnp.abs(ref)))
    assert float(jnp.max(jnp.abs(out - ref))) <= 4e-7 * scale
    # Frozen boundary rows must match the per-step path BITWISE (their
    # interior + frozen-dim cells never change; wrap-dim halo cells of a
    # frozen row are rewritten once from within the row — both paths do
    # it identically), and their frozen-dim interiors must equal the
    # untouched initial values.
    outn, refn, Tn = np.asarray(out), np.asarray(ref), np.asarray(T)
    inner = [slice(1, -1)] * 3
    for d, p in enumerate(periods):
        if p:
            continue
        for edge in (slice(0, 1), slice(-1, None)):
            sl = [slice(None)] * 3
            sl[d] = edge
            assert np.array_equal(outn[tuple(sl)], refn[tuple(sl)]), d
            sli = list(inner)
            sli[d] = edge
            assert np.array_equal(outn[tuple(sli)], Tn[tuple(sli)]), d
    igg.finalize_global_grid()


@pytest.mark.skipif(not _tpu_available(), reason="needs a real TPU chip")
def test_f64_rank4_halo_oracle_on_chip():
    """Rank-4 component-stacked Float64 fields on real hardware: the halo
    dims (0,1,2) miss the lane (component) axis, so this exercises the
    pair-emulated 'dus64' sequential path end-to-end with trailing
    unsharded dims (the reference's rank-generic GGArray in its default
    dtype)."""
    import jax
    import jax.numpy as jnp

    n, C = 32, 2
    with jax.enable_x64(True):
        igg.init_global_grid(n, n, n, dimx=1, dimy=1, dimz=1,
                             periodx=1, periody=1, periodz=1, quiet=True)
        i, j, k, c = np.meshgrid(np.arange(n), np.arange(n), np.arange(n),
                                 np.arange(C), indexing="ij")
        host = (((i * n + j) * n + k) * C + c).astype(np.float64)

        out = np.asarray(igg.update_halo(jnp.asarray(host)))

        exp = host.copy()
        for d in range(3):
            sl_first = [slice(None)] * 4
            sl_last = [slice(None)] * 4
            src_first = [slice(None)] * 4
            src_last = [slice(None)] * 4
            sl_first[d] = 0
            src_first[d] = n - 2
            sl_last[d] = n - 1
            src_last[d] = 1
            exp[tuple(sl_first)] = exp[tuple(src_first)]
            exp[tuple(sl_last)] = exp[tuple(src_last)]
        assert np.array_equal(out, exp), np.argwhere(out != exp)[:5]
        igg.finalize_global_grid()


@pytest.mark.skipif(not _tpu_available(), reason="needs a real TPU chip")
@pytest.mark.parametrize("periods", [(1, 1, 1), (0, 0, 0)],
                         ids=["selfwrap", "open_frozen"])
def test_stokes_trapezoid_matches_per_iteration(periods):
    """The K-iteration Stokes chunk kernel (compiled VMEM-resident bands,
    `igg.ops.stokes_trapezoid._kernel`) against the per-iteration fused
    kernel on the 1-device 128^3 grid — periodic self-wrap (the headline
    benchmark config, x self-extended) and all-open (frozen velocity
    boundary planes).  The window-vs-composition equivalence is pinned on
    CPU meshes by tests/test_stokes_trapezoid.py; this pins the Mosaic
    banded realization against the shipped per-iteration tier."""
    import jax.numpy as jnp

    from igg.models import stokes3d
    from igg.ops.stokes_trapezoid import fit_stokes_K

    igg.init_global_grid(128, 128, 128, dimx=1, dimy=1, dimz=1,
                         periodx=periods[0], periody=periods[1],
                         periodz=periods[2],
                         overlapx=3, overlapy=3, overlapz=3, quiet=True)
    grid = igg.get_global_grid()
    params = stokes3d.Params()
    P, Vx, Vy, Vz, Rho = stokes3d.init_fields(params, dtype=np.float32)
    # Overlap-consistent nontrivial entry (the chunk tier's contract):
    # evolve the coordinate init by a few per-iteration kernel steps.
    pre = stokes3d.make_iteration(params, donate=False, n_inner=3,
                                  trapezoid=False)
    P, Vx, Vy, Vz = pre(P, Vx, Vy, Vz, Rho)

    n_inner = 9          # warm-up + one K=8 chunk
    assert fit_stokes_K(grid, (128, 128, 128), n_inner - 1,
                        np.float32) == 8

    ref = stokes3d.make_iteration(params, donate=False, n_inner=n_inner,
                                  trapezoid=False)
    chk = stokes3d.make_iteration(params, donate=False, n_inner=n_inner,
                                  trapezoid=True)
    r = ref(P, Vx, Vy, Vz, Rho)
    o = chk(P, Vx, Vy, Vz, Rho)
    for name, a, b in zip(("P", "Vx", "Vy", "Vz"), r, o):
        scale = float(jnp.max(jnp.abs(a))) + 1e-30
        rel = float(jnp.max(jnp.abs(a - b))) / scale
        assert rel < 1e-4, (name, rel, periods)
    igg.finalize_global_grid()


@pytest.mark.skipif(not _tpu_available(), reason="needs a real TPU chip")
@pytest.mark.parametrize("periods", [(1, 1, 1), (0, 0, 0)],
                         ids=["selfwrap", "open_frozen"])
def test_hm3d_trapezoid_matches_per_step_kernel(periods):
    """The K-step HM3D chunk kernel (the chunk engine's generic
    VMEM-resident banded kernel, `igg.ops.chunk_engine._resident_kernel`,
    instantiated by `igg.ops.hm3d_trapezoid`) against the per-step fused
    kernel on the 1-device 128^3 grid — periodic self-wrap and all-open
    (both fields' boundary planes frozen).  The window-vs-composition
    equivalence is pinned on CPU meshes by tests/test_chunk_engine.py;
    this pins the compiled banded realization on hardware."""
    import jax.numpy as jnp

    from igg.models import hm3d
    from igg.ops.hm3d_trapezoid import fit_hm3d_K

    igg.init_global_grid(128, 128, 128, dimx=1, dimy=1, dimz=1,
                         periodx=periods[0], periody=periods[1],
                         periodz=periods[2], quiet=True)
    grid = igg.get_global_grid()
    params = hm3d.Params()
    Pe, phi = hm3d.init_fields(params, dtype=np.float32)

    n_inner = 9          # warm-up + one K=8 chunk
    assert fit_hm3d_K(grid, (128, 128, 128), n_inner - 1, np.float32) == 8

    ref = hm3d.make_step(params, donate=False, n_inner=n_inner,
                         trapezoid=False)
    chk = hm3d.make_step(params, donate=False, n_inner=n_inner,
                         trapezoid=True)
    r = ref(Pe, phi)
    o = chk(Pe, phi)
    assert igg.degrade.active().get("hm3d") == "hm3d.trapezoid"
    for name, a, b in zip(("Pe", "phi"), r, o):
        scale = float(jnp.max(jnp.abs(a))) + 1e-30
        rel = float(jnp.max(jnp.abs(a - b))) / scale
        assert rel < 1e-4, (name, rel, periods)
    igg.finalize_global_grid()


@pytest.mark.skipif(not _tpu_available(), reason="needs a real TPU chip")
@pytest.mark.parametrize("periods", [(1, 1), (0, 0)],
                         ids=["periodic", "open"])
def test_wave2d_mosaic_compiled_matches_xla(periods):
    """The fused wave2d per-step kernel, COMPILED (Mosaic whole-block
    program), against the XLA composition on a 1-device grid."""
    import jax.numpy as jnp

    from igg.models import wave2d

    igg.init_global_grid(512, 512, 1, periodx=periods[0],
                         periody=periods[1], quiet=True)
    params = wave2d.Params()
    fields = wave2d.init_fields(params, dtype=np.float32)
    ref = wave2d.make_step(params, donate=False, n_inner=5,
                           use_pallas=False)
    pal = wave2d.make_step(params, donate=False, n_inner=5,
                           use_pallas=True, chunk=False)
    r = ref(*fields)
    o = pal(*fields)
    assert igg.degrade.active().get("wave2d") == "wave2d.mosaic"
    for name, a, b in zip(("P", "Vx", "Vy"), r, o):
        scale = float(jnp.max(jnp.abs(a))) + 1e-30
        rel = float(jnp.max(jnp.abs(a - b))) / scale
        assert rel < 1e-5, (name, rel, periods)
    igg.finalize_global_grid()


@pytest.mark.skipif(not _tpu_available(), reason="needs a real TPU chip")
def test_hm3d_banded_compiled_at_256_where_resident_refuses():
    """Round 18, the tentpole's headline claim ON HARDWARE: at 256^3 f32
    single-device the resident chunk window's working set exceeds the
    VMEM budget (`fit_hm3d_K` == 0), and the STREAMING banded rung —
    x-row band sweeps through a rolling VMEM window with HBM ping-pong —
    serves the chunk tier there anyway, matching the XLA composition."""
    import jax.numpy as jnp

    from igg.models import hm3d
    from igg.ops.hm3d_trapezoid import fit_hm3d_K

    igg.init_global_grid(256, 256, 256, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    grid = igg.get_global_grid()
    assert fit_hm3d_K(grid, (256, 256, 256), 8, np.float32) == 0
    params = hm3d.Params()
    Pe, phi = hm3d.init_fields(params, dtype=np.float32)
    ref = hm3d.make_step(params, donate=False, n_inner=5,
                         use_pallas=False)
    band = hm3d.make_step(params, donate=False, n_inner=5, banded=True,
                          K=4, band=8)
    r = ref(Pe, phi)
    o = band(Pe, phi)
    assert igg.degrade.active().get("hm3d") == "hm3d.banded"
    for name, a, b in zip(("Pe", "phi"), r, o):
        scale = float(jnp.max(jnp.abs(a))) + 1e-30
        rel = float(jnp.max(jnp.abs(a - b))) / scale
        assert rel < 1e-4, (name, rel)
    igg.finalize_global_grid()


@pytest.mark.skipif(not _tpu_available(), reason="needs a real TPU chip")
def test_stokes_banded_compiled_at_256_where_resident_refuses():
    """Same headline claim for the staggered family: 256^3 f32 Stokes,
    where the resident window refuses (`fit_stokes_K` == 0), through the
    compiled banded rung vs the per-iteration fused kernel."""
    import jax.numpy as jnp

    from igg.models import stokes3d
    from igg.ops.stokes_trapezoid import fit_stokes_K

    igg.init_global_grid(256, 256, 256, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1,
                         overlapx=3, overlapy=3, overlapz=3, quiet=True)
    grid = igg.get_global_grid()
    assert fit_stokes_K(grid, (256, 256, 256), 8, np.float32) == 0
    params = stokes3d.Params()
    P, Vx, Vy, Vz, Rho = stokes3d.init_fields(params, dtype=np.float32)
    pre = stokes3d.make_iteration(params, donate=False, n_inner=3,
                                  trapezoid=False)
    P, Vx, Vy, Vz = pre(P, Vx, Vy, Vz, Rho)
    ref = stokes3d.make_iteration(params, donate=False, n_inner=5,
                                  use_pallas=False)
    band = stokes3d.make_iteration(params, donate=False, n_inner=5,
                                   banded=True, K=4, band=8)
    r = ref(P, Vx, Vy, Vz, Rho)
    o = band(P, Vx, Vy, Vz, Rho)
    assert igg.degrade.active().get("stokes3d") == "stokes3d.banded"
    for name, a, b in zip(("P", "Vx", "Vy", "Vz"), r, o):
        scale = float(jnp.max(jnp.abs(a))) + 1e-30
        rel = float(jnp.max(jnp.abs(a - b))) / scale
        # f32 reassociation through the PT chain (see
        # tests/test_chunk_engine.py::test_stokes_banded_matches_xla_staggered).
        assert rel < 5e-4, (name, rel)
    igg.finalize_global_grid()


@pytest.mark.skipif(not _tpu_available(), reason="needs a real TPU chip")
def test_wave2d_banded_compiled_matches_per_step():
    """The 2-D banded rung COMPILED (rolling y-band window) against the
    per-step fused kernel on a 1-device periodic grid."""
    import jax.numpy as jnp

    from igg.models import wave2d

    igg.init_global_grid(512, 512, 1, periodx=1, periody=1, quiet=True)
    params = wave2d.Params()
    fields = wave2d.init_fields(params, dtype=np.float32)
    pre = wave2d.make_step(params, donate=False, n_inner=3,
                           use_pallas=True, chunk=False)
    fields = pre(*fields)
    ref = wave2d.make_step(params, donate=False, n_inner=5,
                           use_pallas=True, chunk=False)
    band = wave2d.make_step(params, donate=False, n_inner=5, banded=True,
                            K=4, band=8)
    r = ref(*fields)
    o = band(*fields)
    assert igg.degrade.active().get("wave2d") == "wave2d.banded"
    for name, a, b in zip(("P", "Vx", "Vy"), r, o):
        scale = float(jnp.max(jnp.abs(a))) + 1e-30
        rel = float(jnp.max(jnp.abs(a - b))) / scale
        assert rel < 1e-4, (name, rel)
    igg.finalize_global_grid()


@pytest.mark.skipif(not _tpu_available(), reason="needs a real TPU chip")
def test_wave2d_chunk_compiled_matches_per_step():
    """The K-step wave2d chunk kernel (compiled whole-window resident
    program, `igg.ops.wave2d_pallas._chunk_kernel`) against the per-step
    fused kernel on a 1-device periodic grid."""
    import jax.numpy as jnp

    from igg.models import wave2d
    from igg.ops.wave2d_pallas import fit_wave2d_K

    igg.init_global_grid(512, 512, 1, periodx=1, periody=1, quiet=True)
    grid = igg.get_global_grid()
    params = wave2d.Params()
    fields = wave2d.init_fields(params, dtype=np.float32)
    pre = wave2d.make_step(params, donate=False, n_inner=3,
                           use_pallas=True, chunk=False)
    fields = pre(*fields)

    n_inner = 9          # warm-up + one K=8 chunk
    assert fit_wave2d_K(grid, (512, 512), n_inner - 1, np.float32) == 8

    ref = wave2d.make_step(params, donate=False, n_inner=n_inner,
                           use_pallas=True, chunk=False)
    chk = wave2d.make_step(params, donate=False, n_inner=n_inner,
                           use_pallas=True, chunk=True)
    r = ref(*fields)
    o = chk(*fields)
    assert igg.degrade.active().get("wave2d") == "wave2d.chunk"
    for name, a, b in zip(("P", "Vx", "Vy"), r, o):
        scale = float(jnp.max(jnp.abs(a))) + 1e-30
        rel = float(jnp.max(jnp.abs(a - b))) / scale
        assert rel < 1e-4, (name, rel)
    igg.finalize_global_grid()
