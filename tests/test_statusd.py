"""The live ops plane (igg/statusd.py) and its round-18 satellites: the
HTTP endpoint routes, readiness semantics (machine-readable reasons,
pinned), the chaos liveness proof (a wedged main loop cannot silence the
endpoint), HBM-gauge honesty, multi-rank snapshot aggregation, the
`# HELP` exposition satellite, run-id'd flight dumps, and the `igg.top`
renderer over both sources."""

import json
import pathlib
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import igg
from igg import comm as icomm
from igg import statusd
from igg import telemetry as tel
from igg import top as itop


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Metrics, the ring, and sessions are process-global; isolate every
    test (the test_telemetry fixture)."""
    tel.reset_metrics()
    tel._ring().clear()
    yield
    for s in list(tel._SESSIONS):
        s.detach()
    tel.reset_metrics()


def _grid(**kw):
    args = dict(periodx=1, periody=1, periodz=1, quiet=True)
    args.update(kw)
    igg.init_global_grid(6, 6, 6, **args)


def _make_step():
    from igg.ops import interior_add

    @igg.sharded
    def step(T):
        lap = (T[:-2, 1:-1, 1:-1] + T[2:, 1:-1, 1:-1]
               + T[1:-1, :-2, 1:-1] + T[1:-1, 2:, 1:-1]
               + T[1:-1, 1:-1, :-2] + T[1:-1, 1:-1, 2:]
               - 6.0 * T[1:-1, 1:-1, 1:-1])
        return igg.update_halo_local(interior_add(T, 0.1 * lap))

    return lambda st: {"T": step(st["T"])}


def _init_state(seed=3):
    rng = np.random.default_rng(seed)
    T = igg.from_local_blocks(lambda c, ls: rng.standard_normal(ls),
                              (6, 6, 6))
    return {"T": igg.update_halo(T)}


def _get(url):
    """(HTTP code, parsed JSON body) — 503 included (urllib raises on
    it, which IS the readiness signal under test)."""
    try:
        with urllib.request.urlopen(url, timeout=5) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get_text(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, r.read().decode()


# ---------------------------------------------------------------------------
# (i) coercion and lifecycle
# ---------------------------------------------------------------------------

def test_as_server_coercion(monkeypatch):
    monkeypatch.delenv("IGG_STATUSD_PORT", raising=False)
    assert statusd.as_server(False) is None
    assert statusd.as_server(None) is None            # env unset -> off
    monkeypatch.setenv("IGG_STATUSD_PORT", "0")
    assert statusd.as_server(None) is None            # env 0 -> off
    monkeypatch.setenv("IGG_STATUSD_PORT", "9137")
    srv = statusd.as_server(None)
    assert isinstance(srv, statusd.StatusServer)
    assert srv.requested_port == 9137 and not srv.started
    srv2 = statusd.as_server(True)
    assert srv2.requested_port == 9137
    srv3 = statusd.as_server(4242)
    assert srv3.requested_port == 4242
    shared = statusd.StatusServer(port=0)
    assert statusd.as_server(shared) is shared
    with pytest.raises(igg.GridError, match="serve="):
        statusd.as_server("nope")


def test_start_stop_releases_port():
    srv = statusd.StatusServer(port=0).start()
    port = srv.port
    assert port and srv.url.endswith(str(port))
    srv.stop()
    # The port is released: an immediate rebind succeeds.
    srv2 = statusd.StatusServer(port=port).start()
    assert srv2.port == port
    srv2.stop()
    srv2.stop()   # idempotent


# ---------------------------------------------------------------------------
# (ii) the routes
# ---------------------------------------------------------------------------

def test_routes_metrics_healthz_status_events():
    tel.counter("igg_steps_total", run="t").inc(7)
    tel.emit("run_started", run="resilient", n_steps=40)
    tel.emit("step_stats", step=20, run="resilient", steps_per_s=5.0,
             ms_per_step=200.0, window_steps=20, fetch_lag_steps=1)
    tel.emit("checkpoint", step=20, path="/tmp/ck_000000020")
    with statusd.StatusServer(port=0) as srv:
        code, body = _get_text(srv.url + "/metrics")
        assert code == 200
        assert 'igg_steps_total{run="t"} 7.0' in body
        code, h = _get(srv.url + "/healthz")
        assert code == 200 and h["live"] and h["ready"]
        assert h["reasons"] == []
        code, s = _get(srv.url + "/status")
        assert code == 200
        run = s["runs"]["resilient"]
        assert run["n_steps"] == 40 and run["steps_done"] == 20
        assert run["steps_per_s"] == 5.0
        assert s["checkpoint"]["step"] == 20
        assert s["health"]["ready"] is True
        assert isinstance(s["tiers"], dict)
        # /events tails the ring as JSONL, ?n= bounded.
        code, nd = _get_text(srv.url + "/events?n=2")
        assert code == 200
        lines = [json.loads(ln) for ln in nd.splitlines()]
        assert len(lines) == 2
        assert all("kind" in r for r in lines)
        code, e = _get(srv.url + "/nope")
        assert code == 404 and "/metrics" in e["routes"]


# ---------------------------------------------------------------------------
# (iii) readiness semantics (reason strings PINNED — treat as API)
# ---------------------------------------------------------------------------

def test_readiness_stall_episode_and_rearm():
    """An active collective-stall episode flips readiness false with
    reason 'collective_stall'; the episode draining re-arms readiness
    without a restart."""
    w = icomm.StallWatchdog(0.01, run="resilient", poll_s=100)
    try:
        with statusd.StatusServer(port=0) as srv:
            code, h = _get(srv.url + "/healthz")
            assert code == 200 and h["ready"]
            w.watch(("probe", 5), 5, "watchdog probe (psum over mesh axes)")
            time.sleep(0.03)
            assert w.check()   # fires: over-age and not ready
            code, h = _get(srv.url + "/healthz")
            assert code == 503 and h["live"] and not h["ready"]
            (reason,) = h["reasons"]
            assert reason["reason"] == "collective_stall"
            assert "watchdog probe" in reason["in_flight"]
            # Drain: the channel empties, the episode re-arms -> ready.
            w.fetched(("probe", 5), 5)
            code, h = _get(srv.url + "/healthz")
            assert code == 200 and h["ready"] and h["reasons"] == []
    finally:
        w.close()


def test_readiness_member_quarantine_all_vs_one():
    """All members quarantined -> not ready ('all_members_quarantined');
    a single quarantined member is degraded but READY."""
    with statusd.StatusServer(port=0) as srv:
        tel.emit("run_started", run="ensemble", n_steps=10, members=3)
        tel.emit("member_quarantined", step=4, member=1, reason="retries")
        code, h = _get(srv.url + "/healthz")
        assert code == 200 and h["ready"]           # 1 of 3: still serving
        tel.emit("member_quarantined", step=6, member=0, reason="retries")
        tel.emit("member_quarantined", step=6, member=2, reason="retries")
        code, h = _get(srv.url + "/healthz")
        assert code == 503
        (reason,) = h["reasons"]
        assert reason["reason"] == "all_members_quarantined"
        assert reason["members"] == 3
        code, s = _get(srv.url + "/status")
        assert s["members"] == {"total": 3, "quarantined": [0, 1, 2]}
        # A fresh ensemble run resets the verdict.
        tel.emit("run_started", run="ensemble", n_steps=10, members=2)
        code, h = _get(srv.url + "/healthz")
        assert code == 200 and h["ready"]


def test_readiness_heal_escalation():
    with statusd.StatusServer(port=0) as srv:
        tel.emit("run_started", run="resilient", n_steps=10)
        tel.emit("heal_escalated", step=7, run="resilient",
                 action="demote", escalated_from="retile",
                 signal_reason="window_inflation", reason="escalation")
        code, h = _get(srv.url + "/healthz")
        assert code == 503
        (reason,) = h["reasons"]
        assert reason["reason"] == "heal_escalated"
        assert reason["escalated_from"] == "retile"
        # The escalation also lands in the /status heal ledger.
        _, s = _get(srv.url + "/status")
        assert any(hh["kind"] == "heal_escalated" for hh in s["heal"])
        # A fresh run resets the terminal verdict.
        tel.emit("run_started", run="resilient", n_steps=10)
        code, h = _get(srv.url + "/healthz")
        assert code == 200 and h["ready"]


def test_readiness_watchdog_fetch_lag():
    with statusd.StatusServer(port=0, max_fetch_lag=100) as srv:
        tel.emit("run_started", run="resilient", n_steps=10_000)
        tel.emit("step_stats", step=500, run="resilient", steps_per_s=9.0,
                 ms_per_step=111.0, window_steps=50, fetch_lag_steps=450)
        code, h = _get(srv.url + "/healthz")
        assert code == 503
        (reason,) = h["reasons"]
        assert reason["reason"] == "watchdog_fetch_lag"
        assert reason["lag_steps"] == 450
        assert reason["max_lag_steps"] == 100
        # The watchdog catching up recovers readiness.
        tel.emit("step_stats", step=1000, run="resilient", steps_per_s=9.0,
                 ms_per_step=111.0, window_steps=50, fetch_lag_steps=10)
        code, h = _get(srv.url + "/healthz")
        assert code == 200 and h["ready"]
        # ...and a FINISHED run's stale lag never trips readiness.
        tel.emit("step_stats", step=1500, run="resilient", steps_per_s=9.0,
                 ms_per_step=111.0, window_steps=50, fetch_lag_steps=999)
        tel.emit("run_finished", step=10_000, run="resilient",
                 preempted=False)
        code, h = _get(srv.url + "/healthz")
        assert code == 200 and h["ready"]


# ---------------------------------------------------------------------------
# (iv) the chaos liveness proof
# ---------------------------------------------------------------------------

def test_endpoint_answers_while_main_loop_is_wedged(monkeypatch):
    """The module contract: with an injected collective stall AND the
    main loop wedged at a dispatch boundary (chaos hold), `/metrics` and
    `/healthz` keep answering from statusd's own threads — readiness
    false naming the stall — and recover to ready once the episode
    drains at end of run."""
    monkeypatch.setenv("IGG_COMM_STALL_TIMEOUT", "0.05")
    _grid()
    step_fn = _make_step()
    srv = statusd.StatusServer(port=0).start()
    plan = igg.chaos.ChaosPlan(hold_at=[(10, 1.0)])
    seen = []     # (code, reasons) snapshots scraped during the run
    done = threading.Event()
    result = {}

    def scrape():
        while not done.is_set():
            try:
                code, h = _get(srv.url + "/healthz")
                mcode, _ = _get_text(srv.url + "/metrics")
                seen.append((code, [r["reason"] for r in h["reasons"]],
                             mcode))
            except OSError:
                pass
            time.sleep(0.01)

    def run():
        with igg.chaos.collective_stall():
            result["res"] = igg.run_resilient(
                step_fn, _init_state(), 20, watch_every=5,
                max_pending_probes=100, serve=srv, chaos=plan,
                install_sigterm=False)

    scraper = threading.Thread(target=scrape, daemon=True)
    runner = threading.Thread(target=run, daemon=True)
    scraper.start()
    runner.start()
    runner.join(timeout=60)
    done.set()
    scraper.join(timeout=10)
    try:
        assert not runner.is_alive()
        assert result["res"].steps_done == 20   # the run itself completed
        # While the loop was wedged inside the hold, the endpoint kept
        # answering — and reported the stall with readiness false.
        stalled = [s for s in seen if s[0] == 503]
        assert stalled, seen
        assert all("collective_stall" in s[1] for s in stalled)
        assert all(s[2] == 200 for s in seen)   # /metrics never went dark
        # The stall event itself is on the record (the heartbeat emits
        # onto the bus; the flight ring has it).
        assert any(r.kind == "collective_stall"
                   for r in tel.flight_recorder())
        # Episode over (watchdog closed at end of run): ready again.
        code, h = _get(srv.url + "/healthz")
        assert code == 200 and h["ready"]
    finally:
        srv.stop()
        igg.finalize_global_grid()


# ---------------------------------------------------------------------------
# (v) HBM gauges: honest omission
# ---------------------------------------------------------------------------

def test_hbm_gauges_honest_omission_and_presence(monkeypatch):
    from igg import device as idevice

    # The real backend on this host (CPU) exposes no allocator stats:
    # the poller reports None and NO igg_hbm_* gauge exists.
    srv = statusd.StatusServer(port=0, hbm_every=0.0).start()
    try:
        code, body = _get_text(srv.url + "/metrics")
        if not idevice.memory_stats():
            assert "igg_hbm_" not in body
            _, s = _get(srv.url + "/status")
            assert s["hbm"] is None
        # With a backend that DOES report (simulated), the gauges and
        # the /status summary appear.
        monkeypatch.setattr(idevice, "memory_stats", lambda devices=None: [
            {"device": "tpu:0", "kind": "TPU v5p",
             "bytes_in_use": 3 * 2**30, "bytes_limit": 16 * 2**30,
             "peak_bytes_in_use": 5 * 2**30}])
        code, body = _get_text(srv.url + "/metrics")
        assert 'igg_hbm_bytes_in_use{device="tpu:0"}' in body
        assert 'igg_hbm_bytes_limit{device="tpu:0"}' in body
        assert 'igg_hbm_watermark_bytes{device="tpu:0"}' in body
        _, s = _get(srv.url + "/status")
        assert s["hbm"]["devices"] == 1
        assert abs(s["hbm"]["pct_in_use"] - 100.0 * 3 / 16) < 1e-9
    finally:
        srv.stop()


def test_hbm_poll_throttle(monkeypatch):
    from igg import device as idevice

    calls = []
    monkeypatch.setattr(idevice, "memory_stats",
                        lambda devices=None: calls.append(1) or [])
    p = statusd._HbmPoller(every=1000.0)
    p.poll()
    p.poll()
    p.poll()
    assert len(calls) == 1          # throttled
    p.poll(force=True)
    assert len(calls) == 2


# ---------------------------------------------------------------------------
# (vi) multi-rank aggregation
# ---------------------------------------------------------------------------

def test_multi_rank_snapshot_publish_and_merge(tmp_path, monkeypatch):
    """A non-zero rank publishes statusd_r<rank>.json; rank 0's
    /metrics merges it into one rank-labelled exposition and /status
    lists the rank."""
    tel.counter("igg_steps_total", run="resilient").inc(11)
    # Publish AS rank 1 (the publisher half of StatusServer).
    monkeypatch.setattr(tel, "_process_cached", 1)
    pub = statusd.StatusServer(port=0, dir=tmp_path)
    out = pub.publish_snapshot()
    assert out == tmp_path / "statusd_r1.json"
    doc = json.loads(out.read_text())
    assert doc["process"] == 1 and isinstance(doc["metrics"], list)
    # Back to rank 0: the endpoint merges the remote snapshot.
    monkeypatch.setattr(tel, "_process_cached", 0)
    with statusd.StatusServer(port=0, dir=tmp_path) as srv:
        code, body = _get_text(srv.url + "/metrics")
        assert code == 200
        assert 'rank="0"' in body and 'rank="1"' in body
        # One TYPE line per name even with two ranks carrying it.
        assert body.count("# TYPE igg_steps_total counter") == 1
        _, s = _get(srv.url + "/status")
        assert "1" in s["ranks"]
    # Half-written snapshots are skipped, not fatal.
    (tmp_path / "statusd_r2.json").write_text("{torn")
    with statusd.StatusServer(port=0, dir=tmp_path) as srv:
        code, body = _get_text(srv.url + "/metrics")
        assert code == 200 and 'rank="1"' in body


def test_publisher_thread_runs_off_rank0(tmp_path, monkeypatch):
    monkeypatch.setattr(tel, "_process_cached", 3)
    srv = statusd.StatusServer(port=0, dir=tmp_path, publish_every=0.02)
    srv.start()
    try:
        assert srv.port is None          # no HTTP server off rank 0
        deadline = time.monotonic() + 5
        while (not (tmp_path / "statusd_r3.json").exists()
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert (tmp_path / "statusd_r3.json").exists()
    finally:
        srv.stop()
        monkeypatch.setattr(tel, "_process_cached", 0)


def test_remote_snapshot_staleness_gate(tmp_path, monkeypatch):
    """A dead rank's — or a previous job's, in a reused telemetry dir —
    leftover snapshot must not merge into /metrics as live data:
    snapshots whose wall stamp is older than a few publish periods are
    skipped (and return once the publisher refreshes them)."""
    tel.counter("igg_steps_total", run="resilient").inc(11)
    monkeypatch.setattr(tel, "_process_cached", 1)
    pub = statusd.StatusServer(port=0, dir=tmp_path)
    out = pub.publish_snapshot()
    monkeypatch.setattr(tel, "_process_cached", 0)
    with statusd.StatusServer(port=0, dir=tmp_path) as srv:
        code, body = _get_text(srv.url + "/metrics")
        assert code == 200 and 'rank="1"' in body      # fresh: merged
        # Age the snapshot an hour: the rank is treated as gone.
        doc = json.loads(out.read_text())
        doc["wall"] = time.time() - 3600
        out.write_text(json.dumps(doc))
        code, body = _get_text(srv.url + "/metrics")
        assert code == 200 and 'rank="1"' not in body
        _, s = _get(srv.url + "/status")
        assert "1" not in s["ranks"]


# ---------------------------------------------------------------------------
# (vii) satellite: # HELP lines, spec-valid exposition
# ---------------------------------------------------------------------------

def _parse_exposition(text):
    """Minimal spec parse: returns {name: (help?, type?)}; asserts every
    sample line belongs to an announced TYPE and HELP precedes TYPE."""
    meta = {}
    announced = set()
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, name, rest = line.split(" ", 3)
            assert name not in announced, f"HELP after TYPE for {name}"
            meta.setdefault(name, {})["help"] = rest
        elif line.startswith("# TYPE "):
            parts = line.split(" ")
            name, kind = parts[2], parts[3]
            assert name not in announced, f"duplicate TYPE for {name}"
            announced.add(name)
            meta.setdefault(name, {})["type"] = kind
        else:
            name = line.split("{")[0].split(" ")[0]
            base = name
            for suffix in ("_count", "_sum", "_min", "_max"):
                if name.endswith(suffix) and name[:-len(suffix)] in \
                        announced:
                    base = name[:-len(suffix)]
                    break
            assert base in announced, f"sample {name} without TYPE"
            float(line.rsplit(" ", 1)[1])
    return meta


def test_prometheus_help_lines_builtin_and_custom():
    tel.counter("igg_steps_total", run="x").inc()
    tel.gauge("my_custom_gauge", help="A custom thing.\nSecond line",
              kind="a").set(1.5)
    tel.histogram("igg_checkpoint_write_seconds").observe(0.1)
    text = tel.prometheus_text()
    meta = _parse_exposition(text)
    # Built-in names carry HELP from the table; the custom one from its
    # registration; newlines are escaped per spec.
    assert meta["igg_steps_total"]["help"].startswith("Steps completed")
    assert meta["igg_steps_total"]["type"] == "counter"
    assert meta["my_custom_gauge"]["help"] == r"A custom thing.\nSecond line"
    assert meta["igg_checkpoint_write_seconds"]["type"] == "summary"
    # Every igg_* built-in that is registered exposes a HELP line.
    for name, m in meta.items():
        if name.startswith("igg_"):
            assert "help" in m, f"{name} missing HELP"


def test_metric_samples_structured():
    tel.counter("igg_steps_total", run="x").inc(2)
    tel.histogram("h_lat", help="lat").observe(1.0)
    samples = {(s["name"], tuple(sorted(s["labels"].items())))
               : s for s in tel.metric_samples()}
    c = samples[("igg_steps_total", (("run", "x"),))]
    assert c["type"] == "counter" and c["value"] == 2.0
    assert c["help"].startswith("Steps completed")
    h = samples[("h_lat", ())]
    assert h["type"] == "histogram" and h["count"] == 1


# ---------------------------------------------------------------------------
# (viii) satellite: run-id'd flight dumps, merge-tool glob
# ---------------------------------------------------------------------------

def test_flight_dump_collision_fixed(tmp_path):
    """Two runs sharing one telemetry dir write DISTINCT dump files (the
    second used to clobber the first); flight_dumps() finds both forms,
    and the merge tool parses a dump passed explicitly."""
    with tel.Telemetry(tmp_path):
        tel.emit("run_started", run="resilient", n_steps=5)
        tel.emit("nan_detected", step=3, counts={"T": 1})
        first = tel.dump_flight_recorder("first failure")
        tel.emit("run_started", run="resilient", n_steps=5)
        second = tel.dump_flight_recorder("second failure")
    assert len(first) == 1 and len(second) == 1
    assert first[0] != second[0]                      # no clobber
    assert first[0].exists() and second[0].exists()
    # A legacy-named dump from an older build is found too.
    legacy = tmp_path / "flight_r0.json"
    legacy.write_text(json.dumps(
        {"reason": "legacy", "process": 0,
         "events": [{"kind": "legacy_marker", "wall": 1.0,
                     "process": 0, "step": None, "payload": {}}]}))
    found = tel.flight_dumps(tmp_path, rank=0)
    assert set(found) == {first[0], second[0], legacy}
    # Merge tool: a dump handed in explicitly contributes its events.
    recs = tel.merge_streams([legacy, first[0]])
    kinds = [r.get("kind") for r in recs]
    assert "legacy_marker" in kinds and "nan_detected" in kinds


# ---------------------------------------------------------------------------
# (ix) igg.top — one renderer over both sources
# ---------------------------------------------------------------------------

def test_top_renders_live_endpoint_and_offline_dir(tmp_path, capsys):
    with tel.Telemetry(tmp_path):
        tel.emit("run_started", run="resilient", n_steps=100)
        tel.emit("step_stats", step=40, run="resilient", steps_per_s=8.0,
                 ms_per_step=125.0, window_steps=20, fetch_lag_steps=0)
        tel.emit("checkpoint", step=40, path="/tmp/ck_000000040")
    with statusd.StatusServer(port=0) as srv:
        rc = itop._main([srv.url, "--once", "--plain"])
        assert rc == 0
        live = capsys.readouterr().out
    assert "READY" in live and "step 40/100" in live
    assert "HBM" in live
    # Same renderer offline, from the artifacts alone.
    rc = itop._main([str(tmp_path), "--once", "--plain", "-n", "5"])
    assert rc == 0
    offline = capsys.readouterr().out
    assert "OFFLINE VIEW" in offline and "step 40/100" in offline
    assert "checkpoint head: step 40" in offline
    assert "step_stats" in offline           # the event tail renders
    # A bad target is a clean CLI error, not a stack trace.
    assert itop._main([str(tmp_path / "missing"), "--once"]) == 2


def test_top_event_tail_bound():
    for i in range(30):
        tel.emit("step_stats", step=i, run="resilient", steps_per_s=1.0,
                 ms_per_step=1.0, window_steps=1, fetch_lag_steps=0)
    with statusd.StatusServer(port=0) as srv:
        status, events = itop.fetch_endpoint(srv.url, n=7)
        assert len(events) == 7
        frame = itop.render(status, events, 7)
        assert "last 7 event(s):" in frame


def test_top_rank_skew_same_run_across_ranks_only():
    """Two different runs' window times on one rank are NOT skew; skew
    is the same run compared across ranks (worst vs median)."""
    status = {"runs": {"resilient": {"ms_per_step": 125.0},
                       "ensemble": {"ms_per_step": 10.0}},
              "ranks": {}}
    assert itop._rank_skew_from_status(status) is None
    status = {"runs": {"resilient": {"ms_per_step": 10.0}},
              "ranks": {"1": {"runs": {"resilient": {"ms_per_step": 14.0}}},
                        "2": {"runs": {"resilient": {"ms_per_step": 10.0}}}}}
    assert itop._rank_skew_from_status(status) == pytest.approx(4.0)
    # The live gauge, when present, wins over the fallback.
    assert itop._rank_skew_from_status({"rank_skew_ms": 2.5}) == 2.5


def test_top_offline_merges_rank0_metrics_with_rank_snapshots(
        tmp_path, monkeypatch):
    """Rank 0 never publishes statusd_r0.json (it serves HTTP); offline,
    its metrics_r0.jsonl must still feed the view NEXT TO other ranks'
    snapshots — the sources merge per rank, they are not exclusive."""
    with tel.Telemetry(tmp_path):
        tel.gauge("igg_exposed_comm_fraction").set(0.25)
        tel.emit("run_started", run="resilient", n_steps=10)
    tel.reset_metrics()
    tel.counter("igg_tier_dispatch_total", family="diffusion3d",
                tier="diffusion3d.xla").inc(5)
    monkeypatch.setattr(tel, "_process_cached", 1)
    statusd.StatusServer(port=0, dir=tmp_path).publish_snapshot()
    monkeypatch.setattr(tel, "_process_cached", 0)
    status, _ = itop.build_from_dir(tmp_path)
    assert status["gauges"]["igg_exposed_comm_fraction"] == 0.25  # rank 0
    assert status["tiers"].get("diffusion3d") == "diffusion3d.xla"  # rank 1


def test_top_live_non_json_endpoint_clean_error(capsys):
    """igg.top pointed at a non-statusd HTTP server (one answering 200
    with HTML) is a clean CLI error, not a JSONDecodeError traceback."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Html(BaseHTTPRequestHandler):
        def do_GET(self):
            body = b"<html>not statusd</html>"
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Html)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        assert itop._main([url, "--once"]) == 2
        assert "did not return JSON" in capsys.readouterr().err
    finally:
        httpd.shutdown()
        httpd.server_close()


# ---------------------------------------------------------------------------
# (x) run-loop wiring
# ---------------------------------------------------------------------------

def test_run_resilient_serve_knob_lifecycle():
    """serve=<port> starts an owned endpoint for the run's duration and
    releases it afterwards; a shared started server is left running."""
    _grid()
    try:
        shared = statusd.StatusServer(port=0).start()
        res = igg.run_resilient(_make_step(), _init_state(), 10,
                                watch_every=5, serve=shared,
                                install_sigterm=False)
        assert res.steps_done == 10
        assert shared.started                 # left running (shared)
        _, s = _get(shared.url + "/status")
        assert s["runs"]["resilient"]["finished"] is True
        # tiers mirrors degrade.active() — this raw igg.sharded step has
        # no ladder family, so the dict is present but may be empty.
        assert isinstance(s["tiers"], dict)
        shared.stop()
        # env-driven off by default: serve=None with no knob set.
        res = igg.run_resilient(_make_step(), _init_state(), 5,
                                watch_every=5, install_sigterm=False)
        assert res.steps_done == 5
    finally:
        igg.finalize_global_grid()


def test_run_fleet_serve_watches_journal(tmp_path):
    """The fleet drain points /status at its queue journal: per-status
    job counts come from the journal itself."""
    from igg.models import diffusion3d as d3

    def make_states(job):
        params = d3.Params()
        T0, Cp = d3.init_fields(params, dtype=np.float32)
        return [{"T": T0, "Cp": Cp}]

    def make_step(job):
        params = d3.Params()
        return d3.make_member_step(params)

    jobs = [igg.Job(name="j1", global_interior=(8, 8, 8), members=1,
                    n_steps=4, make_states=make_states,
                    make_step=make_step, watch_every=0)]
    srv = statusd.StatusServer(port=0).start()
    try:
        res = igg.run_fleet(jobs, tmp_path, serve=srv,
                            install_sigterm=False)
        assert res.jobs["j1"].status == "done"
        _, s = _get(srv.url + "/status")
        assert s["fleet"]["by_status"] == {"done": 1}
        assert s["fleet"]["jobs"] == 1
    finally:
        srv.stop()


def test_serve_bind_failure_does_not_leak_session(tmp_path):
    """A port-bind failure (port already taken) raises a GridError naming
    the address AND must not leak the run-owned telemetry session into
    the process-global sink list."""
    _grid()
    blocker = statusd.StatusServer(port=0).start()
    try:
        with pytest.raises(igg.GridError, match="cannot bind"):
            igg.run_resilient(_make_step(), _init_state(), 5,
                              watch_every=5, telemetry=tmp_path,
                              serve=blocker.port, install_sigterm=False)
        assert tel._SESSIONS == []
    finally:
        blocker.stop()
        igg.finalize_global_grid()


def test_statusd_env_knobs_registered():
    from igg import _env

    for knob in ("IGG_STATUSD_PORT", "IGG_STATUSD_HOST",
                 "IGG_STATUSD_HBM_EVERY", "IGG_STATUSD_MAX_FETCH_LAG",
                 "IGG_STATUSD_PUBLISH_EVERY"):
        assert knob in _env._KNOWN


# ---------------------------------------------------------------------------
# (xi) the serve plane: queue_saturated readiness, POST /jobs, /status
# ---------------------------------------------------------------------------

def test_readiness_queue_saturated_pinned_and_recovers():
    """Admission backpressure is a pinned readiness reason: readiness
    flips 503/'queue_saturated' (with depth/bound) while the serve queue
    is at bound and RECOVERS when the drain clears it."""
    assert statusd.REASON_QUEUE_SATURATED == "queue_saturated"
    with statusd.StatusServer(port=0) as srv:
        code, h = _get(srv.url + "/healthz")
        assert code == 200 and h["ready"]
        srv.health.set_queue_saturated(depth=16, bound=16)
        code, h = _get(srv.url + "/healthz")
        assert code == 503 and h["live"] and not h["ready"]
        (reason,) = h["reasons"]
        assert reason["reason"] == "queue_saturated"
        assert reason["depth"] == 16 and reason["bound"] == 16
        srv.health.set_queue_saturated(None)
        code, h = _get(srv.url + "/healthz")
        assert code == 200 and h["ready"] and h["reasons"] == []


def test_post_jobs_route_verdicts_and_status_tenants():
    """``POST /jobs`` answers the scheduler's admission verdict verbatim
    (201/200/400/429 + JSON body), 404 off-route, 503 with no serving
    scheduler attached; /status gains the per-tenant `serve` section and
    igg.top renders it."""
    from igg.serve import SubmissionResult
    from igg import top as itop2

    def _post(url, data):
        req = urllib.request.Request(url, data=data, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=5) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    verdicts = {
        b'{"name": "ok"}': SubmissionResult(201, "admitted", job="ok",
                                            tenant="t"),
        b'{"name": "dup"}': SubmissionResult(200, "duplicate",
                                             reason="already enqueued"),
        b"{broken": SubmissionResult(400, "rejected",
                                     reason="malformed: bad"),
        b'{"name": "full"}': SubmissionResult(429, "shed",
                                              reason="queue_saturated"),
    }
    stats = {"queue_depth": 2, "queue_bound": 16, "saturated": False,
             "running": ["ok"], "fenced_devices": [3],
             "draining": False,
             "tenants": {"alice": {"queued": 1, "running": 1, "done": 4,
                                   "failed": 0, "quarantined": 0,
                                   "shed": 2, "rejected": 1,
                                   "retries_used": 3, "retry_budget": 8,
                                   "weight": 2.0}}}
    with statusd.StatusServer(port=0) as srv:
        # No scheduler attached: the route answers 503, not 404.
        code, body = _post(srv.url + "/jobs", b"{}")
        assert code == 503 and "no serving scheduler" in body["reason"]
        srv.watch_serve(lambda: stats, lambda raw: verdicts[bytes(raw)])
        for raw, want in verdicts.items():
            code, body = _post(srv.url + "/jobs", raw)
            assert code == want.code and body == want.doc()
        code, body = _post(srv.url + "/elsewhere", b"{}")
        assert code == 404 and "/jobs" in body["routes"]
        # /status: the serve section IS the scheduler's stats doc.
        _, s = _get(srv.url + "/status")
        assert s["serve"] == stats
        # igg.top renders the tenant table from the same doc.
        frame = itop2.render(s, [], 0)
        assert "serve: queue 2/16" in frame and "fenced 3" in frame
        assert "tenant alice" in frame and "shed=2" in frame
        assert "budget 3/8" in frame
        # Detach: the section disappears and POST answers 503 again.
        srv.watch_serve(None, None)
        _, s = _get(srv.url + "/status")
        assert s["serve"] is None
        code, _ = _post(srv.url + "/jobs", b"{}")
        assert code == 503
