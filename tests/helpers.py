"""Shared test helpers: the coordinate-encoding oracle of the reference suite.

The reference fills arrays with globally-encoded coordinates
`z_g*1e2 + y_g*1e1 + x_g`, zeroes the boundary planes, runs `update_halo!`
and asserts the array equals its backup
(`/root/reference/test/test_update_halo.jl:654,685-697`).  The encoding makes
overlapping cells of neighboring blocks carry identical values (staggered and
periodic cases included), so a correct halo exchange exactly restores what
was zeroed.
"""

import numpy as np

import igg


def encoded_block(coords, lshape, d=1.0):
    """Local block filled with z_g*100 + y_g*10 + x_g for grid `coords`;
    trailing (unsharded) dims beyond the third — e.g. the component axis of
    a rank-4 `(nx,ny,nz,C)` field — add `1000*index` per trailing dim, so
    overlapping cells of neighboring blocks still carry identical values
    component by component."""
    probe = np.empty(lshape)  # carries local shape/ndim for the *_g tools
    nd = len(lshape)
    xs = np.array([igg.x_g(i, d, probe, coords) for i in range(lshape[0])])
    out = xs
    if nd >= 2:
        ys = np.array([igg.y_g(i, d, probe, coords) for i in range(lshape[1])])
        out = out[:, None] + 10.0 * ys[None, :]
    if nd >= 3:
        zs = np.array([igg.z_g(i, d, probe, coords) for i in range(lshape[2])])
        out = out[:, :, None] + 100.0 * zs[None, None, :]
    for extra in range(3, nd):
        out = (out[..., None]
               + 1000.0 * np.arange(lshape[extra]).reshape(
                   (1,) * extra + (lshape[extra],)))
    return out


def encoded_field(lshape, dtype=np.float64):
    """Stacked grid array with every block coordinate-encoded."""
    return igg.from_local_blocks(
        lambda coords, ls: encoded_block(coords, ls), lshape, dtype=dtype)


def halo_dims(lshape):
    """Array dims that have a halo (ol >= 2), cf.
    `/root/reference/src/update_halo.jl:284`."""
    g = igg.get_global_grid()
    return [d for d in range(min(len(lshape), igg.NDIMS))
            if g.ol_of_local(d, lshape) >= 2]


def zero_halo_blocks(stacked, lshape):
    """Zero the outermost planes of every local block in every halo dim."""
    g = igg.get_global_grid()
    out = np.array(stacked)
    nd = len(lshape)
    dims = [g.dims[d] if d < igg.NDIMS else 1 for d in range(nd)]
    hdims = halo_dims(lshape)
    for cz in range(dims[2] if nd > 2 else 1):
        for cy in range(dims[1] if nd > 1 else 1):
            for cx in range(dims[0]):
                sl = tuple(slice(c * s, (c + 1) * s)
                           for c, s in zip((cx, cy, cz)[:nd], lshape))
                block = out[sl]
                for d in hdims:
                    ix = [slice(None)] * nd
                    ix[d] = 0
                    block[tuple(ix)] = 0.0
                    ix[d] = lshape[d] - 1
                    block[tuple(ix)] = 0.0
    return out


def expected_after_update(backup, zeroed, lshape):
    """Expected result of update_halo on the zeroed field: the backup, except
    that edge blocks of non-periodic dims keep their zeroed outer plane
    (open-boundary no-write, `/root/reference/test/test_update_halo.jl:727-732`)."""
    g = igg.get_global_grid()
    out = np.array(backup)
    nd = len(lshape)
    dims = [g.dims[d] if d < igg.NDIMS else 1 for d in range(nd)]
    hdims = halo_dims(lshape)
    for cz in range(dims[2] if nd > 2 else 1):
        for cy in range(dims[1] if nd > 1 else 1):
            for cx in range(dims[0]):
                c = (cx, cy, cz)
                sl = tuple(slice(cc * s, (cc + 1) * s)
                           for cc, s in zip(c[:nd], lshape))
                for d in hdims:
                    if g.periods[d]:
                        continue
                    if c[d] == 0:
                        ix = [slice(None)] * nd
                        ix[d] = 0
                        out[sl][tuple(ix)] = zeroed[sl][tuple(ix)]
                    if c[d] == dims[d] - 1:
                        ix = [slice(None)] * nd
                        ix[d] = lshape[d] - 1
                        out[sl][tuple(ix)] = zeroed[sl][tuple(ix)]
    return out


def assert_halo_agreement(stacked, lshape):
    """Post-exchange halo-agreement invariant: along every halo dimension,
    a block's ol-deep overlap region must equal the owning neighbor's
    interior — rows `[s-ol, s)` of the left block are the same global
    cells as rows `[0, ol)` of its right neighbor (wrap pairs included on
    periodic dims; a single-device periodic dim self-wraps, so the
    block's own first and last ol rows must agree).  This is the
    invariant the degradation ladder's verify-on-first-use guard leans on
    (`igg.degrade`): a fast tier whose exchange breaks it diverges from
    the XLA composition truth on the very next stencil application."""
    g = igg.get_global_grid()
    out = np.asarray(stacked)
    nd = len(lshape)
    dims = [g.dims[d] if d < igg.NDIMS else 1 for d in range(nd)]

    def block(coords):
        sl = tuple(slice(c * s, (c + 1) * s)
                   for c, s in zip(coords, lshape[:len(coords)]))
        return out[sl]

    sharded_nd = min(nd, igg.NDIMS)
    for d in halo_dims(lshape):
        ol = g.ol_of_local(d, lshape)
        s = lshape[d]
        pairs = [(c, c + 1) for c in range(dims[d] - 1)]
        if g.periods[d]:
            pairs.append((dims[d] - 1, 0))   # wrap (self-wrap when dims=1)
        for coords in np.ndindex(*dims[:sharded_nd]):
            if coords[d] != 0:
                continue   # enumerate each cross-line of blocks once
            for cl, cr in pairs:
                left = list(coords)
                right = list(coords)
                left[d], right[d] = cl, cr
                lb, rb = block(tuple(left)), block(tuple(right))
                take = lambda b, lo, hi: b[
                    (slice(None),) * d + (slice(lo, hi),)]
                np.testing.assert_array_equal(
                    take(lb, s - ol, s), take(rb, 0, ol),
                    err_msg=(f"halo disagreement along dim {d} between "
                             f"blocks {tuple(left)} and {tuple(right)}"))


def ensemble_member_step(rate=0.1):
    """The standard ensemble test harness: a radius-1 Laplacian relaxation
    as a LOCAL member step over the `{"T": ...}` state dict — the
    :func:`igg.run_ensemble` contract (vmapped over the member axis inside
    one shard_map program; an extra per-member scalar `"rate_scale"`
    field, when present, scales the relaxation rate — the parameter-sweep
    shape)."""
    from igg.ops import interior_add

    def member_step(st):
        T = st["T"]
        lap = (T[:-2, 1:-1, 1:-1] + T[2:, 1:-1, 1:-1]
               + T[1:-1, :-2, 1:-1] + T[1:-1, 2:, 1:-1]
               + T[1:-1, 1:-1, :-2] + T[1:-1, 1:-1, 2:]
               - 6.0 * T[1:-1, 1:-1, 1:-1])
        r = rate * st["rate_scale"] if "rate_scale" in st else rate
        out = dict(st)
        out["T"] = igg.update_halo_local(interior_add(T, r * lap))
        return out

    return member_step


def ensemble_states(members, lshape=(6, 6, 6), seed=3, rate_scales=None):
    """M member state dicts with deterministic random interiors (halos
    exchanged so every member starts globally consistent); with
    `rate_scales` each member also carries a per-member scalar
    `"rate_scale"` parameter field."""
    rng = np.random.default_rng(seed)
    out = []
    for m in range(members):
        T = igg.from_local_blocks(
            lambda c, ls: rng.standard_normal(ls), lshape)
        st = {"T": igg.update_halo(T)}
        if rate_scales is not None:
            st["rate_scale"] = np.float64(rate_scales[m])
        out.append(st)
    return out


def roundtrip(lshape, dtype=np.float64):
    """Run the full oracle: encode → zero halos → update_halo → (result,
    expected)."""
    import jax
    field = encoded_field(lshape, dtype=dtype)
    backup = np.array(field)
    zeroed = zero_halo_blocks(backup, lshape)
    A = jax.device_put(zeroed, igg.sharding_for(len(lshape)))
    out = np.array(igg.update_halo(A))
    return out, expected_after_update(backup, zeroed, lshape)
