"""wave2d model tests: staggered multi-field halo exchange in anger."""

import numpy as np

import igg
from igg.models import wave2d


def _run(nt, nx, ny, **kw):
    igg.init_global_grid(nx, ny, 1, periodx=1, periody=1, quiet=True, **kw)
    params = wave2d.Params()
    P, Vx, Vy = wave2d.init_fields(params, dtype=np.float64)
    step = wave2d.make_step(params, donate=False)
    for _ in range(nt):
        P, Vx, Vy = step(P, Vx, Vy)
    out = tuple(igg.gather_interior(a) for a in (P, Vx, Vy))
    igg.finalize_global_grid()
    return out


def test_decomposition_invariance():
    multi = _run(20, 6, 6)   # dims (4,2,1): periodic global 4*(6-2) x 2*(6-2) = 16x8
    # same global size on one device: 1*(nx-2) = 16, 1*(ny-2) = 8
    single = _run(20, 18, 10, dimx=1, dimy=1, dimz=1)
    for m, s, name in zip(multi, single, "P Vx Vy".split()):
        assert m.shape == s.shape, name
        np.testing.assert_allclose(m, s, atol=1e-12, err_msg=name)


def test_wave_propagates_and_stays_bounded():
    igg.init_global_grid(8, 8, 1, periodx=1, periody=1, quiet=True)
    params = wave2d.Params()
    P, Vx, Vy = wave2d.init_fields(params, dtype=np.float64)
    P0 = igg.gather_interior(P)
    step = wave2d.make_step(params, donate=False)
    for _ in range(50):
        P, Vx, Vy = step(P, Vx, Vy)
    P1 = igg.gather_interior(P)
    assert np.isfinite(P1).all()
    assert np.max(np.abs(P1)) <= 1.5 * np.max(np.abs(P0))  # CFL-stable
    assert np.max(np.abs(P1 - P0)) > 1e-6  # it moved
