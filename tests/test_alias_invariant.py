"""Periodic alias invariants of the fused step (ADVICE round-1, finding 2).

After one step on a fully-periodic single-device grid, every halo plane must
equal its aliased interior plane (`T_new[0] == T_new[s-2]`, etc. — the
reference's halo copy guarantees this bitwise).  Measured behavior of the
fused Pallas step on real TPU (v5e, 64x64x128 f32):

  - y/z planes: exact — they are in-VMEM copies of the interior planes
    (`igg.ops.diffusion_pallas._make_kernel`, wrap mode);
  - x planes: equal to 1 ulp (max |diff| 1.5e-8 f32) — the halo planes are
    computed by XLA outside the kernel while their aliased interiors are
    computed by Mosaic inside, and the two compilers contract FMAs
    differently.  The portable XLA path is exact on all six planes.

This file pins the exact-by-construction planes in interpret mode and
bounds the x planes at 1-ulp scale.
"""

import numpy as np

import igg
from igg.models import diffusion3d as d3


def test_fused_step_alias_invariants_interpret():
    igg.init_global_grid(8, 16, 128, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    params = d3.Params(lx=4.0, ly=8.0, lz=60.0)
    T, Cp = d3.init_fields(params, dtype=np.float32)
    step = d3.make_step(params, donate=False, use_pallas=True,
                        pallas_interpret=True)
    Tn = np.asarray(step(T, Cp))

    # y/z halo planes are in-VMEM copies of their aliased interiors: exact.
    np.testing.assert_array_equal(Tn[:, 0], Tn[:, -2])
    np.testing.assert_array_equal(Tn[:, -1], Tn[:, 1])
    np.testing.assert_array_equal(Tn[:, :, 0], Tn[:, :, -2])
    np.testing.assert_array_equal(Tn[:, :, -1], Tn[:, :, 1])
    # x halo planes come from a separately-compiled computation: 1-ulp bound
    # (exact on CPU interpret, 1.5e-8 observed on TPU Mosaic-vs-XLA).
    scale = np.max(np.abs(Tn))
    assert np.max(np.abs(Tn[0] - Tn[-2])) <= 4e-7 * scale
    assert np.max(np.abs(Tn[-1] - Tn[1])) <= 4e-7 * scale


def test_xla_step_alias_invariants_exact():
    igg.init_global_grid(8, 16, 128, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    params = d3.Params(lx=4.0, ly=8.0, lz=60.0)
    T, Cp = d3.init_fields(params, dtype=np.float32)
    step = d3.make_step(params, donate=False, use_pallas=False)
    Tn = np.asarray(step(T, Cp))
    for a, b in [(Tn[0], Tn[-2]), (Tn[-1], Tn[1]),
                 (Tn[:, 0], Tn[:, -2]), (Tn[:, -1], Tn[:, 1]),
                 (Tn[:, :, 0], Tn[:, :, -2]), (Tn[:, :, -1], Tn[:, :, 1])]:
        np.testing.assert_array_equal(a, b)
