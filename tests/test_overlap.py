"""hide_communication: overlap-restructured step vs the plain composition.

The contract (igg/overlap.py): for slice-based computes (whose
outermost-plane values read only in-slab cells — every model stencil) the
result is identical to `update_halo_local(compute(A))` *everywhere*,
including open-boundary planes the compute writes (the no-write fallback
planes are slab-computed, round 4) and full-shape updates.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import igg


def stencil(A):
    """Radius-1 shift-invariant SLICE-based stencil (accepts any extent,
    writes its full shape: edge planes get the base term plus whatever
    in-slab neighbor terms exist — so slab-window values equal full-array
    values, the property the open-boundary fallback planes rely on)."""
    out = 0.1 * A
    for d in range(A.ndim):
        lo = [slice(None)] * A.ndim
        hi = [slice(None)] * A.ndim
        mid = [slice(None)] * A.ndim
        lo[d], hi[d], mid[d] = slice(0, -2), slice(2, None), slice(1, -1)
        out = out.at[tuple(mid)].add(0.15 * (A[tuple(lo)] + A[tuple(hi)]))
    return out


def coord_filled(shape, dx=1.0):
    A = igg.zeros(shape)
    X, Y, Z = igg.coord_fields(dx, dx, dx, A)
    return A + X * 10000 + Y * 100 + Z + 0.5


@pytest.mark.parametrize("periods", [(1, 1, 1), (0, 0, 0), (1, 0, 1)])
def test_matches_composition(eight_devices, periods):
    igg.init_global_grid(6, 6, 6, periodx=periods[0], periody=periods[1],
                         periodz=periods[2], quiet=True)
    A0 = coord_filled((6, 6, 6))

    @igg.sharded
    def step_plain(A):
        return igg.update_halo_local(stencil(A))

    @igg.sharded
    def step_overlap(A):
        return igg.hide_communication(A, stencil)

    plain = np.asarray(step_plain(A0))
    over = np.asarray(step_overlap(A0))

    # Strict contract (round 4): for slice-based computes the overlapped
    # form agrees with the plain composition everywhere, INCLUDING the open
    # global-boundary planes (the fallback planes are slab-computed, so
    # full-shape writes to the outermost planes survive exactly as in the
    # plain composition).
    np.testing.assert_allclose(plain, over, rtol=1e-12, atol=1e-9)
    igg.finalize_global_grid()


def test_multiple_steps_periodic_exact(eight_devices):
    """Overlapped and plain steps agree to FP tolerance over many steps on a
    fully periodic grid (the halo cells feed back into the stencil; the
    two program shapes may fuse/FMA-contract differently, so equality is
    numerical rather than bitwise)."""
    igg.init_global_grid(6, 6, 6, periodx=1, periody=1, periodz=1, quiet=True)
    A = B = coord_filled((6, 6, 6))

    @igg.sharded
    def step_plain(A):
        return igg.update_halo_local(stencil(A))

    @igg.sharded
    def step_overlap(A):
        return igg.hide_communication(A, stencil)

    for _ in range(5):
        A = step_plain(A)
        B = step_overlap(B)
    np.testing.assert_allclose(np.asarray(A), np.asarray(B),
                               rtol=1e-12, atol=1e-9)
    igg.finalize_global_grid()


def test_staggered_and_2d(eight_devices):
    """Staggered field (nx+1) and a 2-D field go through the same contract."""
    igg.init_global_grid(6, 6, 6, periodx=1, periody=1, periodz=1, quiet=True)
    Vx = coord_filled((7, 6, 6))

    @igg.sharded
    def step_plain(A):
        return igg.update_halo_local(stencil(A))

    @igg.sharded
    def step_overlap(A):
        return igg.hide_communication(A, stencil)

    np.testing.assert_allclose(np.asarray(step_plain(Vx)),
                               np.asarray(step_overlap(Vx)),
                               rtol=1e-12, atol=1e-9)
    igg.finalize_global_grid()


def test_radius_too_large_raises(eight_devices):
    igg.init_global_grid(8, 8, 8, periodx=1, periody=1, periodz=1, quiet=True)
    A = igg.zeros((8, 8, 8))
    with pytest.raises(igg.GridError, match="radius"):
        @igg.sharded
        def step(A):
            return igg.hide_communication(A, stencil, radius=2)
        step(A)
    igg.finalize_global_grid()


def test_diffusion_model_overlap_matches(eight_devices):
    """The flagship model run with overlap=True agrees with the plain path."""
    from igg.models import diffusion3d as d3
    igg.init_global_grid(8, 8, 8, periodx=1, periody=1, periodz=1, quiet=True)
    p = d3.Params()
    T0, Cp = d3.init_fields(p, dtype=np.float64)
    plain = d3.make_multi_step(3, p, donate=False, use_pallas=False)
    over = d3.make_multi_step(3, p, donate=False, use_pallas=False,
                              overlap=True)
    np.testing.assert_allclose(np.asarray(plain(T0, Cp)),
                               np.asarray(over(T0, Cp)),
                               rtol=1e-12, atol=1e-12)
    igg.finalize_global_grid()


def test_self_neighbor_axis(eight_devices):
    """A periodic dimension with one device along it takes the plane-level
    self-neighbor local-copy path inside hide_communication (the analog of
    `/root/reference/src/update_halo.jl:516-532`)."""
    igg.init_global_grid(6, 6, 6, dimx=4, dimy=1, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    A0 = coord_filled((6, 6, 6))

    @igg.sharded
    def step_plain(A):
        return igg.update_halo_local(stencil(A))

    @igg.sharded
    def step_overlap(A):
        return igg.hide_communication(A, stencil)

    np.testing.assert_allclose(np.asarray(step_plain(A0)),
                               np.asarray(step_overlap(A0)),
                               rtol=1e-12, atol=1e-9)
    igg.finalize_global_grid()


def test_multi_field_negative_stagger_base(eight_devices):
    """Primaries where a field is staggered SMALLER than the base field
    (df < 0): the slab window must extend below the base send plane, or the
    smaller field's send plane silently carries pre-compute values.  Coupled
    face/center pair with the face field first (base), fully periodic ->
    hidden must match plain compute-then-exchange."""
    igg.init_global_grid(8, 8, 8, periodx=1, periody=1, periodz=1,
                         overlapx=3, overlapy=3, overlapz=3, quiet=True)

    def comp(Vx, P):
        div = Vx[1:, :, :] - Vx[:-1, :, :]          # centered on P cells
        Pn = P.at[1:-1, 1:-1, 1:-1].add(-0.1 * div[1:-1, 1:-1, 1:-1])
        gr = P[1:, :, :] - P[:-1, :, :]             # on interior Vx faces
        Vn = Vx.at[1:-1, 1:-1, 1:-1].add(-0.1 * gr[:, 1:-1, 1:-1])
        return Vn, Pn

    import jax.numpy as jnp
    P0 = igg.zeros((8, 8, 8), dtype=np.float64)
    X, Y, Z = igg.coord_fields(1.0, 1.0, 1.0, P0)
    P0 = P0 + jnp.sin(X) + jnp.cos(2 * Y) + Z * 0.1
    Vx0 = igg.zeros((9, 8, 8), dtype=np.float64) + 0.5

    @igg.sharded
    def step_plain(Vx, P):
        return igg.update_halo_local(*comp(Vx, P))

    @igg.sharded
    def step_hidden(Vx, P):
        return igg.hide_communication((Vx, P), comp)

    for _ in range(3):
        Vx_p, P_p = step_plain(Vx0, P0)
        Vx_h, P_h = step_hidden(Vx0, P0)
        Vx0, P0 = Vx_p, P_p
    np.testing.assert_allclose(np.asarray(P_h), np.asarray(P_p),
                               rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(np.asarray(Vx_h), np.asarray(Vx_p),
                               rtol=1e-12, atol=1e-12)
    igg.finalize_global_grid()


def test_writer_assembly_matches_xla(eight_devices):
    """hide_communication's Pallas-writer assembly (the TPU default) vs the
    XLA plans, driven on the CPU mesh via the interpret seam — pins the
    spec building in `igg.halo.assemble_field` (squeeze axes, dim order,
    corner ownership) that otherwise only runs on real TPU hardware."""
    from igg import halo

    # Writer-eligible local shape: lane dim aligned (>= 2*128), sublane
    # tile-aligned.
    igg.init_global_grid(8, 16, 256, periodx=1, periody=1, periodz=1,
                         quiet=True)
    A0 = coord_filled((8, 16, 256))

    @igg.sharded
    def step_xla(A):
        return igg.hide_communication(A, stencil, assembly="xla")

    xla = np.asarray(step_xla(A0))
    halo._FORCE_WRITER_INTERPRET = True
    try:
        @igg.sharded
        def step_writer(A):
            return igg.hide_communication(A, stencil)

        writer = np.asarray(step_writer(A0))
    finally:
        halo._FORCE_WRITER_INTERPRET = False
    np.testing.assert_array_equal(writer, xla)
    igg.finalize_global_grid()


def test_invalid_assembly_rejected(eight_devices):
    igg.init_global_grid(6, 6, 6, periodx=1, quiet=True)
    A = igg.zeros((6, 6, 6))
    with pytest.raises(igg.GridError, match="assembly="):
        igg.update_halo(A, assembly="XLA")
